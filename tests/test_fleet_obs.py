"""Fleet observability plane tests (ISSUE 20): bounded span export
that never blocks a heartbeat, the fleet ``/metrics`` merge preserving
every pinned per-process series, event-journal ring wraparound with
monotone seqs, SLO burn-rate math against hand-computed windows, the
relay-tree trace_id propagation fix (a leaf's trace_id must appear in
master-side spans), and the stitched-trace e2e on a 1-balancer/
2-replica fleet."""

import json
import re
import time
import urllib.request

import numpy as np
import pytest

from znicz_tpu import telemetry
from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.telemetry.events import EventJournal, FleetEventStore
from znicz_tpu.telemetry.fleet import (FleetMetricsStore, FleetTraceStore,
                                       SloTracker, SpanExporter,
                                       registry_snapshot,
                                       render_fleet_prometheus)
from znicz_tpu.telemetry.trace import TraceRing


# -- span export: bounded, drops-oldest, never blocks ------------------------


def test_span_exporter_bounded_drops_oldest_and_filters():
    ring = TraceRing(capacity=4096, enabled=True)
    exp = SpanExporter("rep@1", capacity=8)
    ring.add_sink(exp)
    t0 = time.perf_counter()
    # spans WITHOUT a trace_id never enter the export buffer
    for i in range(5):
        ring.add("serving", "untraced", t0, 0.001)
    assert exp.pending() == 0
    for i in range(20):
        ring.add("serving", f"s{i}", t0, 0.001, {"trace_id": f"t{i}"})
    # bounded at capacity; the OLDEST spans were evicted, counted
    assert exp.pending() == 8
    assert exp.dropped == 12 and exp.offered == 20
    batch = exp.drain(limit=3)
    assert [s["name"] for s in batch] == ["s12", "s13", "s14"]
    assert exp.pending() == 5
    # drain-all empties; a second drain is a cheap no-op
    assert len(exp.drain()) == 5
    assert exp.drain() == []
    # peek is non-destructive and trace-scoped
    ring.add("serving", "mine", t0, 0.002, {"trace_id": "T"})
    ring.add("serving", "other", t0, 0.002, {"trace_id": "U"})
    assert [s["name"] for s in exp.peek_trace("T")] == ["mine"]
    assert exp.pending() == 2


def test_span_export_never_blocks_heartbeat_carrier():
    """A flooded exporter must keep the heartbeat path O(batch): the
    drain is bounded by span_export_batch and the buffer sheds oldest
    under pressure rather than growing or stalling."""
    ring = TraceRing(capacity=1 << 15, enabled=True)
    exp = SpanExporter("rep@1", capacity=256)
    ring.add_sink(exp)
    t0 = time.perf_counter()
    for i in range(10_000):
        ring.add("serving", "flood", t0, 0.0, {"trace_id": f"t{i}"})
    assert exp.pending() == 256             # bounded under flood
    t1 = time.perf_counter()
    batch = exp.drain(128)                  # one carrier's worth
    dt = time.perf_counter() - t1
    assert len(batch) == 128 and dt < 0.5
    assert exp.dropped == 10_000 - 256


# -- fleet /metrics merge -----------------------------------------------------


def _validate_exposition(text: str):
    """Strict exposition shape (the test_telemetry discipline): every
    sample line's metric name must be TYPEd exactly once."""
    typed = {}
    for line in text.splitlines():
        if line.startswith("# TYPE"):
            _, _, name, kind = line.split()
            assert name not in typed, f"duplicate TYPE for {name}"
            typed[name] = kind
    n = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name = re.split(r"[{ ]", line, 1)[0]
        base = re.sub(r"_(sum|count|total|bucket)$", "", name)
        assert name in typed or base in typed, f"untyped sample {line!r}"
        n += 1
    return n


def test_fleet_metrics_merge_preserves_local_series_and_members():
    from znicz_tpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry()
    sc = reg.scope("serving")
    c = sc.counter("served", "requests served")
    c.inc(7)
    h = sc.histogram("request_latency_seconds", "latency")
    h.observe(0.25)
    local = reg.render_prometheus()

    member = MetricsRegistry()
    msc = member.scope("serving")
    msc.counter("served", "requests served").inc(3)
    msc.counter("rejected", "requests refused").inc(1)
    store = FleetMetricsStore()
    store.update("r0@999", registry_snapshot(member))

    text = render_fleet_prometheus(reg, store)
    _validate_exposition(text)
    # every LOCAL series line survives verbatim in the merged superset
    for line in local.splitlines():
        if line and not line.startswith("#"):
            assert line in text, f"local series lost: {line!r}"
    # member children appear under the same family with member=<origin>
    assert re.search(r'^znicz_served_total\{[^}]*member="r0@999"[^}]*\} 3',
                     text, re.M)
    # member-only families land at the end, TYPEd once
    assert re.search(r'^znicz_rejected_total\{[^}]*member="r0@999"', text,
                     re.M)
    # the structured rollup sums counters across members
    roll = store.rollup()
    json.loads(json.dumps(roll))
    fam = roll["families"]["znicz_served_total"]
    assert fam["members"]["r0@999"] == 3.0


def test_fleet_metrics_store_tolerates_wire_garbage():
    store = FleetMetricsStore()
    for garbage in (None, 17, "families", [], {"nope": 1}):
        store.update("evil@1", garbage)     # silently ignored
    assert store.members() == {}


# -- event journal ------------------------------------------------------------


def test_event_ring_wraparound_keeps_seq_monotone():
    j = EventJournal(capacity=8, origin="m@1")
    seqs = [j.emit("failover", "serving", i=i) for i in range(30)]
    assert seqs == list(range(1, 31))       # monotone despite wraparound
    assert j.dropped == 22
    events = j.since(0)
    assert len(events) == 8
    assert [e["seq"] for e in events] == list(range(23, 31))
    # the gap is detectable: oldest retained seq > a stale cursor
    assert events[0]["seq"] > 5
    # non-primitive fields are coerced, not raised
    j.emit("rollback", "serving", why={"complex": object()})
    assert isinstance(j.since(30)[0]["why"], str)


def test_fleet_event_store_dedups_and_assigns_monotone_mseq():
    store = FleetEventStore(capacity=64)
    a = EventJournal(capacity=16, origin="a@1")
    b = EventJournal(capacity=16, origin="b@2")
    for i in range(3):
        a.emit("failover", "serving", i=i)
        b.emit("autoscale_up", "serving", i=i)
    batch_a = a.since(0)
    assert store.ingest("a@1", batch_a) == 3
    # re-delivered piggyback batch (sender retry): ingested ZERO times
    assert store.ingest("a@1", batch_a) == 0
    assert store.ingest("b@2", b.since(0)) == 3
    merged = store.since(0)
    assert [e["mseq"] for e in merged] == list(range(1, 7))
    assert store.cursor("a@1") == 3
    # a fresh event after the cursor merges exactly once
    a.emit("rollback", "serving")
    assert store.ingest("a@1", a.since(store.cursor("a@1"))) == 1


# -- SLO burn math ------------------------------------------------------------


def test_slo_burn_rates_match_hand_computed_windows():
    now = [1000.0]
    slo = SloTracker("serving", window_fast_s=60.0, window_slow_s=600.0,
                     bucket_s=5.0, clock=lambda: now[0])
    slo.add_objective("availability", target=0.99)
    # slow window: 95 good + 5 bad spread over 500s
    for i in range(100):
        now[0] = 1000.0 + i * 5.0
        slo.record("availability", ok=(i % 20 != 0))
    now[0] = 1000.0 + 99 * 5.0
    # hand-computed: fast window (60s) holds the last 12 buckets ->
    # one bad (i=80 at t=1400 is outside; i=... the bads land every
    # 100s, so exactly 0 or 1 in the fast window). Compute explicitly:
    lo_fast = int((now[0] - 60.0) / 5.0)
    fast_obs = [i for i in range(100) if int((1000.0 + i * 5.0) / 5.0)
                > lo_fast]
    fast_bad = sum(1 for i in fast_obs if i % 20 == 0)
    want_fast = (fast_bad / len(fast_obs)) / 0.01 \
        if fast_obs else None
    got_fast = slo.burn_rate("availability", 60.0)
    assert got_fast == pytest.approx(want_fast)
    lo_slow = int((now[0] - 600.0) / 5.0)
    slow_obs = [i for i in range(100) if int((1000.0 + i * 5.0) / 5.0)
                > lo_slow]
    slow_bad = sum(1 for i in slow_obs if i % 20 == 0)
    want_slow = (slow_bad / len(slow_obs)) / 0.01
    assert slo.burn_rate("availability", 600.0) == \
        pytest.approx(want_slow)
    snap = slo.snapshot()
    obj = snap["objectives"]["availability"]
    assert obj["fast_burn"] == pytest.approx(want_fast)
    assert obj["slow_burn"] == pytest.approx(want_slow)
    # state matrix: fast>=1 and slow>=1 -> burning; fast only -> warn
    assert obj["state"] == ("burning" if want_fast is not None
                            and want_fast >= 1.0 and want_slow >= 1.0
                            else "warn" if want_fast is not None
                            and want_fast >= 1.0 else "ok")
    want_remaining = 1.0 - (slow_bad / len(slow_obs)) / 0.01
    assert obj["budget_remaining"] == pytest.approx(
        max(-1.0, min(1.0, want_remaining)))   # clamped for the panel


def test_slo_latency_objective_and_empty_windows():
    now = [0.0]
    slo = SloTracker("serving", clock=lambda: now[0])
    slo.add_objective("p99", target=0.9, threshold=0.250, unit="s")
    # no observations: burn is None, state ok, budget intact
    assert slo.burn_rate("p99", 60.0) is None
    assert slo.snapshot()["objectives"]["p99"]["state"] == "ok"
    now[0] = 10.0
    for lat in (0.1, 0.2, 0.3, 0.4):        # 2 good, 2 bad vs 250ms
        slo.record_latency("p99", lat)
    assert slo.burn_rate("p99", 60.0) == pytest.approx(
        (2 / 4) / 0.1)                      # bad_frac / error budget
    # a latency feed for an objective WITHOUT a threshold is a no-op
    slo.add_objective("availability", target=0.99)
    slo.record_latency("availability", 5.0)
    assert slo.burn_rate("availability", 60.0) is None


# -- relay-tree trace_id propagation (ISSUE 20 satellite) ---------------------


def _tiny_wf(tmp_path):
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 120
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.common.dirs.snapshots = str(tmp_path)
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def test_leaf_trace_id_reaches_master_side_spans(tmp_path):
    """A leaf's trace_id travels the contributor manifest through a
    relay flush and lands on master-side ``aggregate_contrib`` spans,
    and the relay's own edge-validate span is tagged with it — the
    training half of cross-process stitching."""
    from znicz_tpu.network_common import handshake_request
    from znicz_tpu.parallel.relay import Relay
    from znicz_tpu.server import Server

    telemetry.set_enabled(True)
    telemetry.tracer().clear()
    wf = _tiny_wf(tmp_path)
    server = Server(wf)
    msg = handshake_request(wf)
    del msg["cmd"]
    assert server._handle({"cmd": "register", "id": "obs-relay",
                           "relay": True, **msg})["ok"]
    job = server._handle({"cmd": "job", "id": "obs-relay", "count": 1})
    job = job if "job_id" in job else dict(job, **job.get("jobs", [{}])[0])
    jid, tid = job["job_id"], job["trace_id"]
    assert tid

    relay = Relay("tcp://127.0.0.1:1", "tcp://127.0.0.1:2",
                  relay_id="obs-relay", fanout=3, flush_s=999.0)
    relay._cred = (3, "cafebabecafebabe")
    now = time.time()
    for sid in ("s0", "s1", "s2"):      # flush threshold never crossed
        relay._children[sid] = now
    shapes = {f.name: {k: a.shape for k, a in f.params().items()}
              for f in wf.forwards if f.has_weights}
    deltas = {n: {k: np.full(s, 1e-4, np.float32)
                  for k, s in layer.items()}
              for n, layer in shapes.items()}
    rep = relay._child_update({"cmd": "update", "id": "s0",
                               "job_id": jid, "trace_id": tid,
                               "deltas": deltas,
                               "metrics": {"loss": 1.0, "n_err": 0}},
                              "s0")
    assert rep["ok"]
    # the relay's edge-validate span carries the contributor's trace_id
    edge = [e for e in telemetry.tracer().events()
            if e[0] == "relay" and e[1] == "edge_validate"
            and e[5] and e[5].get("trace_id") == tid]
    assert edge, "edge_validate span must carry the leaf trace_id"
    up = server._handle(dict(
        relay._flush_message(list(relay._buffer), dict(relay._sum)),
        cmd="update", id="obs-relay"))
    assert up["ok"] and up["outcomes"][jid] == "ok"
    # ... and the master parents one span per contributor to it
    master = [e for e in telemetry.tracer().events()
              if e[0] == "master" and e[1] == "aggregate_contrib"
              and e[5] and e[5].get("trace_id") == tid]
    assert master, "leaf trace_id must appear in master-side spans"
    assert master[0][5]["leaf"] == "s0"


def test_relay_flush_forwards_leaf_obs_payloads():
    """Spans/events a leaf piggybacked on its update must survive the
    relay hop: buffered (bounded) and re-shipped upstream as
    ``fwd_obs`` with the LEAF's origin intact."""
    from znicz_tpu.parallel.relay import Relay

    relay = Relay("tcp://127.0.0.1:1", "tcp://127.0.0.1:2",
                  relay_id="fwd-relay", fanout=3, flush_s=999.0)
    relay._cred = (3, "cafebabecafebabe")
    now = time.time()
    for sid in ("s0", "s1", "s2"):      # flush threshold never crossed
        relay._children[sid] = now
    leaf_spans = [{"cat": "train", "name": "minibatch", "ts": 1,
                   "dur": 2, "tid": 0, "args": {"trace_id": "T-1"}}]
    leaf_events = [{"kind": "preemption", "plane": "training",
                    "seq": 1, "ts": 0.0, "origin": "slave-7@42"}]
    rep = relay._child_update({"cmd": "update", "id": "s0", "job_id": 1,
                               "trace_id": "T-1", "spans": leaf_spans,
                               "events": leaf_events,
                               "origin": "slave-7@42",
                               "metrics": {"loss": 1.0}}, "s0")
    assert rep["ok"]
    with relay._lock:
        fwd = list(relay._obs_fwd)
    assert fwd and fwd[0]["origin"] == "slave-7@42"
    assert fwd[0]["spans"] == leaf_spans
    # bounded drop-oldest: a flood of child payloads keeps the newest
    for i in range(100):
        relay._buffer_child_obs({"spans": [{"cat": "t", "name": f"n{i}",
                                            "ts": 0, "dur": 0,
                                            "tid": 0}],
                                 "origin": f"s{i}@1"}, f"s{i}")
    with relay._lock:
        assert len(relay._obs_fwd) == 32
        assert relay._obs_fwd[-1]["origin"] == "s99@1"


# -- stitched-trace e2e (1 balancer / 2 replicas) -----------------------------


def test_stitched_trace_e2e_balancer_two_replicas(tmp_path):
    """The serving half of the tentpole, end to end over real sockets:
    client -> balancer -> real replica frontends, spans exported on
    heartbeats/replies/self-drain, assembled by trace_id in the fleet
    store, with the fleet endpoints serving the merged views."""
    from znicz_tpu.serving import (InferenceClient, InferenceServer,
                                   ReplicaBalancer)
    from znicz_tpu.web_status import WebStatus

    telemetry.set_enabled(True)
    bal = ReplicaBalancer(replica_ttl_s=2.0, heartbeat_s=0.2).start()
    wf = _tiny_wf(tmp_path)
    srvs = [InferenceServer(wf, max_batch=4, max_delay_ms=1.0,
                            announce=bal.endpoint,
                            replica_id=f"obs-r{i}").start()
            for i in range(2)]
    cli = InferenceClient(bal.endpoint, timeout=20.0,
                          breaker_failures=0)
    status = WebStatus(port=0).start()
    base = f"http://127.0.0.1:{status.port}"
    try:
        t0 = time.time()
        while bal.ready_count() < 2:
            assert time.time() - t0 < 30, "fleet never became ready"
            time.sleep(0.05)
        x = np.zeros((1, 28 * 28), np.float32)
        store = telemetry.fleet_trace()
        deadline = time.time() + 30
        stitched = (None, [])
        while time.time() < deadline:
            rep = cli.result(cli.submit(x))
            assert rep["lb"] and rep["ok"]
            time.sleep(0.05)
            stitched = store.best_stitched()
            if len(stitched[1]) >= 3:
                break
        tid, origins = stitched
        assert len(origins) >= 3, f"stitched only {origins}"
        # the merged Chrome trace renders one pid per origin
        chrome = store.chrome_trace(tid)
        json.loads(json.dumps(chrome))
        assert sorted(chrome["fleet"]["origins"]) == sorted(origins)
        names = {ev["name"] for ev in chrome["traceEvents"]}
        assert "request" in names           # client/balancer side
        # both replicas eventually contribute spans to the store
        all_origins = {o for o, _ in store.spans()}
        deadline = time.time() + 20
        while time.time() < deadline and not any(
                o.startswith("obs-r1") or o.startswith("obs-r0")
                for o in all_origins):
            cli.result(cli.submit(x))
            time.sleep(0.05)
            all_origins = {o for o, _ in store.spans()}
        assert any(o.startswith("obs-r") for o in all_origins), \
            f"no replica-origin spans in {all_origins}"
        # fleet endpoints: merged /metrics keeps pinned local series
        # AND carries member rows; /events.json + /slo.json are JSON
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            text = r.read().decode()
        _validate_exposition(text)
        assert re.search(r'member="', text), \
            "fleet-merged /metrics has no member series"
        for series in ("znicz_served_total", "znicz_requests_in_total"):
            assert re.search(rf"^{series}\{{", text, re.M), series
        with urllib.request.urlopen(f"{base}/trace.json?fleet=1",
                                    timeout=10) as r:
            fleet_trace = json.loads(r.read().decode())
        assert fleet_trace["fleet"]["origins"]
        with urllib.request.urlopen(f"{base}/slo.json", timeout=10) as r:
            slo = json.loads(r.read().decode())
        assert "serving" in slo["planes"]
        with urllib.request.urlopen(f"{base}/events.json?fleet=1",
                                    timeout=10) as r:
            json.loads(r.read().decode())
    finally:
        status.stop()
        cli.close()
        for s in srvs:
            s.stop()
        bal.stop()
