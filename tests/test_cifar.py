"""CIFAR sample e2e (BASELINE config[1] gate): StandardWorkflow declarative
build trains the 3-conv+2-fc net and beats chance comfortably."""

import numpy as np
import pytest

from znicz_tpu.core.config import root


@pytest.fixture
def small_cifar(tmp_path):
    root.cifar.loader.n_train = 300
    root.cifar.loader.n_valid = 100
    root.cifar.loader.n_test = 0
    root.cifar.loader.minibatch_size = 50
    root.cifar.decision.max_epochs = 8
    root.common.dirs.snapshots = str(tmp_path)
    yield


def test_cifar_trains(small_cifar):
    from znicz_tpu.samples import cifar

    wf = cifar.run()
    dec = wf.decision
    assert bool(dec.complete)
    valid = dec.epoch_metrics[1]
    assert valid is not None
    # 10-class chance = 90% err.  The r3 difficulty tier (datasets.py:
    # one cue per class, overlapping jitter, distractor grating) leaves
    # the full anchor config at ~41% err and this shrunk config at ~67%
    # — assert "beats chance clearly" with margin for platform variance.
    assert valid["err_pct"] < 78.0, valid


def test_cifar_graph_shapes(small_cifar):
    from znicz_tpu.samples import cifar

    wf = cifar.CifarWorkflow()
    wf.initialize(device=None)
    shapes = [tuple(f.output.shape) for f in wf.forwards]
    assert shapes[0] == (50, 32, 32, 16)      # conv 5x5 pad 2
    assert shapes[1] == (50, 16, 16, 16)      # max pool 2x2
    assert shapes[2] == (50, 16, 16, 16)      # LRN
    assert shapes[4] == (50, 8, 8, 32)        # avg pool
    assert shapes[6] == (50, 4, 4, 32)        # avg pool
    assert shapes[7] == (50, 64)              # fc tanh
    assert shapes[8] == (50, 10)              # softmax
    # every trainable layer got a GD twin in reverse order
    assert len(wf.gds) == len(wf.forwards)
    assert wf.gds[0].forward is wf.forwards[-1]
    assert wf.gds[-1].forward is wf.forwards[0]
