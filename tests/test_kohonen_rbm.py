"""Kohonen SOM + RBM units (BASELINE config[3] behavioral-parity gate)."""

import numpy as np

from znicz_tpu.core.config import root
from znicz_tpu.kohonen import KohonenForward, KohonenTrainer, grid_coords
from znicz_tpu.memory import Array
from znicz_tpu.rbm import Binarization, GradientRBM


def test_kohonen_forward_winner_oracle():
    rng = np.random.default_rng(23)
    x = rng.normal(size=(6, 4)).astype(np.float32)
    fwd = KohonenForward(name="kf", shape=(3, 3))
    fwd.input = Array(x)
    fwd.initialize(device=None)
    fwd.run()
    w = fwd.weights.mem
    want = np.argmin(((x[:, None, :] - w[None]) ** 2).sum(-1), axis=1)
    got = np.array(fwd.output.map_read())
    np.testing.assert_array_equal(got, want)
    hits = np.array(fwd.hits.map_read())
    assert hits.sum() == 6
    assert fwd.total == 6


def test_kohonen_trainer_moves_winner_toward_sample():
    x = np.array([[1.0, 1.0]], np.float32)
    tr = KohonenTrainer(name="kt", shape=(2, 2), learning_rate=0.5,
                        radius=0.5, decay_epochs=1e9)
    tr.input = Array(x)
    tr.batch_size = 1
    tr.initialize(device=None)
    w0 = tr.weights.mem.copy()
    d0 = ((w0 - x) ** 2).sum(1)
    win = int(np.argmin(d0))
    tr.run()
    w1 = np.array(tr.weights.map_read())
    d1 = ((w1 - x) ** 2).sum(1)
    assert d1[win] < d0[win]          # winner moved toward the sample
    assert tr.qerror > 0


def test_kohonen_forward_masks_padded_tail():
    """With batch_size < buffer rows, padded duplicates must not count."""
    rng = np.random.default_rng(24)
    x = rng.normal(size=(5, 3)).astype(np.float32)
    fwd = KohonenForward(name="kfm", shape=(2, 2))
    fwd.input = Array(x)
    fwd.batch_size = 3
    fwd.initialize(device=None)
    fwd.run()
    assert fwd.total == 3
    assert np.array(fwd.hits.map_read()).sum() == 3


def test_kohonen_grid_coords():
    c = grid_coords(2, 3)
    assert c.shape == (6, 2)
    np.testing.assert_allclose(c[0], [0, 0])
    np.testing.assert_allclose(c[-1], [1, 2])


def test_kohonen_sample_organizes(tmp_path):
    root.kohonen.loader.n_train = 300
    root.kohonen.loader.minibatch_size = 50
    root.kohonen.decision.max_epochs = 8
    from znicz_tpu.samples import kohonen

    wf = kohonen.run()
    q = wf.decision.epoch_qerror
    assert len(q) == 8
    assert q[-1] < q[0] * 0.5, q       # quantization error halves
    # hit map covers a decent fraction of the 8x8 grid
    wf.forward.reset_hits()
    wf.loader.reset()
    for _ in range(6):
        wf.loader.run()
        wf.forward.run()
    hits = np.array(wf.forward.hits.map_read())
    assert hits.sum() == 300
    assert (hits > 0).sum() >= 10      # winners spread over the map


def test_binarization_bernoulli():
    p = np.full((2000,), 0.3, np.float32).reshape(100, 20)
    b = Binarization(name="bin")
    b.input = Array(p)
    b.initialize(device=None)
    b.run()
    out = np.array(b.output.map_read())
    assert set(np.unique(out)) <= {0.0, 1.0}
    assert 0.25 < out.mean() < 0.35


def test_rbm_cd1_reduces_reconstruction_error():
    from znicz_tpu.all2all import All2AllSigmoid

    rng = np.random.default_rng(29)
    # two binary prototype patterns + noise
    protos = (rng.random(size=(2, 16)) > 0.5).astype(np.float32)
    data = protos[rng.integers(0, 2, size=64)]
    flip = rng.random(size=data.shape) < 0.05
    data = np.abs(data - flip.astype(np.float32))

    hidden = All2AllSigmoid(name="rbm_h", output_sample_shape=(8,))
    hidden.input = Array(data)
    hidden.initialize(device=None)
    gr = GradientRBM(name="rbm_gd", hidden=hidden, learning_rate=0.2)
    gr.input = Array(data)
    gr.batch_size = 64
    gr.initialize(device=None)
    errs = []
    for _ in range(30):
        gr.run()
        errs.append(gr.reconstruction_error)
    assert errs[-1] < errs[0] * 0.7, (errs[0], errs[-1])