"""Unified async transport core (ISSUE 14): one fault model for every
plane.

Covers: the RetryPolicy/CircuitBreaker/Endpoint primitives (constants
preserved per plane), the new robustness the unification bought —
training-client fail-fast breaker, master per-slave ingress admission,
training-job deadline propagation — the ``partition`` chaos kind, the
byte-identity regression proof (wire frames, resume snapshot dicts,
``/status.json`` counter names unchanged by the port), and the
cross-plane chaos soak driving master + relay + frontend + balancer
through the SAME FaultSchedule seed (lean here; full soak behind
``slow``)."""

import hashlib
import pickle
import threading
import time
from collections import Counter as _Counter

import numpy as np
import pytest

from znicz_tpu.core.config import root

SEED = 14


def _make_workflow(tmp_path, max_epochs=2, n_train=120):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = n_train
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = max_epochs
    root.common.dirs.snapshots = str(tmp_path)
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def _handshake_fields(workflow):
    from znicz_tpu.network_common import handshake_request

    msg = handshake_request(workflow)
    del msg["cmd"]
    return msg


class _EmptyWorkflow:
    """The minimal object ``workflow_digest`` accepts — client-side
    tests that never reach compute need no real graph."""

    forwards = ()
    gds = ()


class _ScriptedMaster:
    """A scripted REP peer: ``script(req) -> reply dict`` (or the
    string ``"die"`` to close the socket and go silent — the client
    sees pure timeouts from then on)."""

    def __init__(self, script):
        self.script = script
        self.endpoint = None
        self.requests = []
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        assert self._ready.wait(10)

    def _loop(self):
        import zmq

        from znicz_tpu.parallel import wire

        sock = zmq.Context.instance().socket(zmq.REP)
        sock.setsockopt(zmq.LINGER, 0)
        sock.bind("tcp://127.0.0.1:*")
        self.endpoint = sock.getsockopt(zmq.LAST_ENDPOINT).decode()
        self._ready.set()
        try:
            while True:
                raw = sock.recv_multipart()
                req, _ = wire.decode_message(raw)
                self.requests.append(req)
                rep = self.script(req)
                if rep == "die":
                    return
                frames, _ = wire.encode_message(rep)
                sock.send_multipart(frames)
        finally:
            sock.close(0)

    def join(self, timeout=30):
        self._thread.join(timeout)


# -- RetryPolicy: one backoff curve, per-plane constants -----------------------


def test_retry_policy_constants_preserved_per_plane():
    from znicz_tpu.transport import RetryPolicy

    train = RetryPolicy.for_training_client(jitter_key="s1/backoff")
    # client.py's historical curve: 0.25 doubling to the 5s cap
    assert [train.delay(n) for n in (1, 2, 3, 4, 5, 6, 99)] == \
        [0.25, 0.5, 1.0, 2.0, 4.0, 5.0, 5.0]
    assert train.spent(9) and not train.spent(8)
    relay = RetryPolicy.for_relay_upstream()
    # relay.py's historical curve: 0.05 doubling to 2.0, exponent <= 5
    assert [relay.delay(n) for n in (1, 2, 5, 6, 7, 99)] == \
        [0.05, 0.1, 0.8, 1.6, 1.6, 1.6]
    brk = RetryPolicy.for_breaker(0.5, 30.0)
    # serving/client.py's breaker backoff: un-jittered doubling
    assert [brk.jittered(n) for n in (1, 2, 3)] == [0.5, 1.0, 2.0]
    # jitter is deterministic per key (fleet de-sync, replayable)
    a = RetryPolicy.for_training_client(jitter_key="k")
    b = RetryPolicy.for_training_client(jitter_key="k")
    seq_a = [a.jittered(n) for n in range(1, 6)]
    assert seq_a == [b.jittered(n) for n in range(1, 6)]
    assert all(0.5 * a.delay(n) <= seq_a[n - 1] <= 1.5 * a.delay(n)
               for n in range(1, 6))


def test_circuit_breaker_open_probe_close_cycle():
    from znicz_tpu.transport import (CircuitBreaker, CircuitOpenError,
                                     RetryPolicy)

    events = []
    brk = CircuitBreaker(window=4, threshold=2,
                         backoff=RetryPolicy.for_breaker(0.05, 1.0),
                         on_event=events.append, peer="unit")
    brk.record("a", False)
    brk.record("b", False)
    assert brk.state == "open" and events == ["open"]
    with pytest.raises(CircuitOpenError, match="circuit open"):
        brk.admit()
    assert events[-1] == "short_circuit"
    time.sleep(0.07)                    # backoff expires -> half-open
    brk.admit()
    assert brk.state == "half_open"
    assert brk.arm_probe("p1") and brk.probe == "p1"
    with pytest.raises(CircuitOpenError, match="half-open"):
        brk.admit()                     # one probe at a time
    brk.record("p1", True)              # probe success closes + resets
    assert brk.state == "closed" and brk.failure_counts() == (0, 0)
    # a failed probe re-opens with the DOUBLED backoff
    brk.record("a", False)
    brk.record("b", False)
    time.sleep(0.07)
    brk.admit()
    brk.arm_probe("p2")
    brk.record("p2", False)
    assert brk.state == "open"
    assert brk.remaining() > 0.05       # second open: 2 x 0.05 window


# -- Endpoint: the one client fault model --------------------------------------


def test_endpoint_fault_model_and_resend_same_bytes():
    from znicz_tpu.parallel import wire
    from znicz_tpu.transport import BadReply, Endpoint, PeerTimeout

    mode = {"v": "garbage"}

    def script(req):
        if mode["v"] == "garbage":
            return {"_": GarbageOnTheWire()}
        return {"ok": True, "echo": req.get("n")}

    class GarbageOnTheWire:
        def __reduce__(self):           # decodes on the wire to a raise
            return (_raise, ())

    master = _ScriptedMaster(script)
    ep = Endpoint(master.endpoint, recv_timeout_s=0.4)
    frames, _ = wire.encode_message({"cmd": "ping", "n": 7})
    frames = [bytes(f) for f in frames]
    with pytest.raises(BadReply):
        ep.rpc(list(frames))
    assert not ep.connected             # EFSM: fresh socket next call
    mode["v"] = "sane"
    # resend-same-bytes: the SAME frames, new socket, clean reply
    assert ep.rpc(list(frames))["echo"] == 7
    # silence -> PeerTimeout
    mode["v"] = "die"

    def die_script(req):
        return "die"

    master.script = die_script
    with pytest.raises(PeerTimeout):
        ep.rpc(list(frames))
    ep.close()
    master.join()


def _raise():
    raise ValueError("scripted wire garbage")


# -- partition: the seeded drop-ALL window (ISSUE 14 satellite) ----------------


def test_partition_windows_deterministic_and_independent():
    from znicz_tpu.parallel.chaos import FaultSchedule

    a = FaultSchedule(SEED, drop=0.1, corrupt=0.1,
                      partition_s=(0.2, 0.4), partition_gap_s=(0.3, 0.6))
    b = FaultSchedule(SEED, drop=0.1, corrupt=0.1,
                      partition_s=(0.2, 0.4), partition_gap_s=(0.3, 0.6))
    assert a.partition_windows("req", 5) == b.partition_windows("req", 5)
    # per-direction streams differ; both are ordered and disjoint
    assert a.partition_windows("req", 5) != a.partition_windows("rep", 5)
    for direction in ("req", "rep"):
        wins = a.partition_windows(direction, 6)
        for (s0, e0), (s1, e1) in zip(wins, wins[1:]):
            assert e0 < s1
        for s, e in wins:
            assert 0.2 <= e - s <= 0.4
            assert a.in_partition(direction, (s + e) / 2)
            assert not a.in_partition(direction, s - 0.01)
            assert not a.in_partition(direction, e + 0.01)
    # adding partitions leaves the wire stream byte-identical
    plain = FaultSchedule(SEED, drop=0.1, corrupt=0.1)
    assert a.decisions(300) == plain.decisions(300)
    assert not plain.in_partition("req", 1.0)       # disabled
    with pytest.raises(ValueError, match="partition"):
        FaultSchedule(1, partition_s=(0.4, 0.2))
    with pytest.raises(ValueError, match="gap"):
        FaultSchedule(1, partition_s=(0.1, 0.2),
                      partition_gap_s=(0.0, 0.1))


def test_chaos_proxy_partition_drops_whole_window():
    """A real network partition through the proxy: EVERY frame of the
    partitioned direction is dropped for the window (counted
    ``partition``, distinct from per-message ``drop``), and the
    unified reconnect path rides it out — traffic flows again after
    the window closes."""
    from znicz_tpu.parallel import wire
    from znicz_tpu.parallel.chaos import ChaosProxy, FaultSchedule
    from znicz_tpu.transport import Endpoint, PeerTimeout

    master = _ScriptedMaster(lambda req: {"ok": True})
    # one deterministic req-direction window: gap 0.2s, duration 0.6s
    sched = FaultSchedule(SEED, partition_s=(0.6, 0.6),
                          partition_gap_s=(0.2, 0.2))
    front = f"tcp://127.0.0.1:{_free_port()}"
    proxy = ChaosProxy(front, master.endpoint, sched).start()
    ep = Endpoint(front, recv_timeout_s=0.15)
    frames, _ = wire.encode_message({"cmd": "ping"})
    frames = [bytes(f) for f in frames]
    outcomes = []
    t0 = time.time()
    try:
        while time.time() - t0 < 1.6:
            try:
                ep.rpc(list(frames))
                outcomes.append((time.time() - t0, True))
            except PeerTimeout:
                outcomes.append((time.time() - t0, False))
        counters = proxy.counters
        assert counters["req"]["partition"] > 0
        # windows (lo == hi makes them exact): [0.2, 0.8) and
        # [1.0, 1.6) — inside a window NOTHING got through; in the
        # pre-window and inter-window gaps traffic flowed again
        assert any(ok for t, ok in outcomes if t < 0.2)
        assert not any(ok for t, ok in outcomes if 0.25 < t < 0.75)
        assert any(ok for t, ok in outcomes if 0.82 < t < 0.98)
        assert not any(ok for t, ok in outcomes if 1.05 < t < 1.55)
        assert any(a == "partition" for _, d, a in proxy.log
                   if d == "req")
    finally:
        ep.close()
        proxy.stop()
        master.script = lambda req: "die"


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- TransportLoop: dispatch + built-in faults ---------------------------------


def test_transport_loop_rep_dispatch_ticks_and_builtin_faults():
    from znicz_tpu.parallel import wire
    from znicz_tpu.parallel.chaos import FaultSchedule
    from znicz_tpu.transport import (Endpoint, TransportLoop,
                                     bad_frame_reply)

    loop = TransportLoop("unit_test_plane")
    ticks = []

    def reply_fn(frames):
        try:
            req, _ = wire.decode_message(frames)
        except wire.WireError as exc:
            out, _ = wire.encode_message(bad_frame_reply(exc))
            return out
        out, _ = wire.encode_message({"ok": True, "n": req.get("n")})
        return out

    sock = loop.bind_rep("tcp://127.0.0.1:*")
    endpoint = loop.resolved_endpoint(sock)
    loop.register(sock, reply_fn, reply=True)
    loop.add_tick(lambda: ticks.append(1))
    # drop=1.0 would starve a REP peer forever — the hook REMAPS drop
    # to corrupt on lockstep sockets, so the refusal path answers
    loop.inject_faults(FaultSchedule(3, drop=0.49, corrupt=0.5))
    t = threading.Thread(target=loop.run, kwargs={"poll_ms": 5},
                         daemon=True)
    t.start()
    ep = Endpoint(endpoint, recv_timeout_s=2.0)
    try:
        for n in range(6):
            rep = ep.rpc_message({"cmd": "ping", "n": n})
            # every message was corrupted -> every reply is the SHARED
            # refusal slug (wording from transport.bad_frame_reply)
            assert rep["bad_frame"] is True
            assert rep["error"].startswith("bad frame: ")
        counts = loop.fault_counts()
        assert counts["corrupt"] == loop.messages == 6
        assert counts["drop"] == 0      # remapped, counted as done
        assert ticks                    # idle ticks ran
    finally:
        loop.stop()
        t.join(10)
        loop.close()
        ep.close()


# -- the new robustness the unification bought (acceptance criteria) -----------


def test_training_client_fail_fast_breaker():
    """A dead master opens the training client's breaker: later
    attempts are refused LOCALLY (no socket, no recv-timeout burn) and
    the prefetcher shares the same verdict — while the give-up budget
    still counts real probe failures, so run() returns bounded."""
    from znicz_tpu.client import Client

    master = _ScriptedMaster(
        lambda req: {"ok": True, "version": 3, "class_lengths": [1, 1]}
        if req.get("cmd") == "register" else "die")
    client = Client(_EmptyWorkflow(), endpoint=master.endpoint,
                    slave_id="brk")
    root.common.engine.slave_breaker_failures = 2
    t0 = time.perf_counter()
    try:
        done = client.run(poll_sleep=0.01, recv_timeout=0.25,
                          max_reconnects=4, backoff_base=0.02,
                          backoff_cap=0.1, connect_retries=3)
    finally:
        root.common.engine.slave_breaker_failures = 4
    elapsed = time.perf_counter() - t0
    assert done == 0
    # the breaker opened on the dead master and RE-opened on every
    # failed probe; the give-up stayed bounded by the probe budget
    assert client._m["breaker_opens"].value >= 2
    assert client.breaker is not None and client.breaker.state == "open"
    # fail-fast is live right now: an attempt inside the open window
    # is refused locally — no socket, no recv-timeout burn (this is
    # what the prefetcher and any other call site shares)
    from znicz_tpu.transport import CircuitOpenError

    with pytest.raises(CircuitOpenError, match="circuit open"):
        client.breaker.admit()
    assert client._m["breaker_short_circuits"].value >= 1
    # bounded: 5 real probes x (0.25s timeout + <=0.15 jittered
    # backoff) — nowhere near the un-breakered worst case
    assert elapsed < 8.0
    master.join()


def test_master_per_slave_ingress_admission(tmp_path):
    """The serving plane's TokenBucket on the master's door: a job-
    request flood is answered ``wait`` (counted, policy-slugged), the
    slave keeps its membership AND its finished work is still taken —
    refused-as-wait, never fatal."""
    from znicz_tpu.server import Server

    wf = _make_workflow(tmp_path)
    root.common.engine.ingress_rate_limit = 3.0
    try:
        srv = Server(wf, endpoint="tcp://127.0.0.1:0")
    finally:
        root.common.engine.ingress_rate_limit = 0.0
    assert srv._handle({"cmd": "register", "id": "s1",
                        **_handshake_fields(wf)})["ok"]
    replies = [srv._handle({"cmd": "job", "id": "s1"})
               for _ in range(12)]
    jobs = [r for r in replies if "job" in r or "jobs" in r]
    limited = [r for r in replies if r.get("rate_limited")]
    assert jobs and limited
    assert all(r.get("wait") and r.get("policy") == "rate_limited"
               for r in limited)
    assert srv.rate_limited_ingress == len(limited)
    # never fatal: still a registered member, and its UPDATE (finished
    # work) is admitted even while the job bucket is empty
    assert "s1" in srv.registered
    job = jobs[0]
    rep = srv._handle({"cmd": "update", "id": "s1",
                       "job_id": job["job_id"], "deltas": None,
                       "metrics": {"loss": 1.0, "n_err": 1}})
    assert rep["ok"] is True
    # the bucket refills: a paced slave passes admission again (the
    # reply may still be the epoch-tail ``wait`` — what matters is
    # that the RATE LIMIT no longer refuses it)
    time.sleep(0.5)
    n_limited = srv.rate_limited_ingress
    assert not srv._handle({"cmd": "job", "id": "s1"}).get(
        "rate_limited")
    assert srv.rate_limited_ingress == n_limited


def test_training_job_deadline_stamped_and_dropped(tmp_path):
    """Deadline propagation on the training plane (PR 6's 'expired
    work never computed', fleet-wide): the master stamps a budget on
    every job; a client drops an expired job UNCOMPUTED; a relay drops
    expired queued jobs UNSERVED and re-stamps the remaining budget."""
    from znicz_tpu.client import Client
    from znicz_tpu.parallel.relay import Relay
    from znicz_tpu.server import Server

    # (a) the master stamps deadline_ms = the live reap timeout
    wf = _make_workflow(tmp_path)
    srv = Server(wf, endpoint="tcp://127.0.0.1:0", job_timeout=7.5)
    assert srv._handle({"cmd": "register", "id": "s1",
                        **_handshake_fields(wf)})["ok"]
    job = srv._handle({"cmd": "job", "id": "s1"})
    assert job["deadline_ms"] == pytest.approx(7500.0)

    # (b) the client drops an expired job uncomputed and moves on
    def script(req):
        if req.get("cmd") == "register":
            return {"ok": True, "version": 3, "class_lengths": [1, 1]}
        if req.get("cmd") == "job":
            if script.served:
                return {"done": True}
            script.served = True
            return {"job_id": 1, "job": {"class": 0, "size": 1},
                    "params": {}, "train": False, "deadline_ms": 0.0}
        return {"ok": True}

    script.served = False
    master = _ScriptedMaster(script)
    client = Client(_EmptyWorkflow(), endpoint=master.endpoint,
                    slave_id="ddl")
    root.common.engine.job_prefetch = False
    try:
        done = client.run(poll_sleep=0.01, recv_timeout=2.0)
    finally:
        root.common.engine.job_prefetch = True
    assert done == 0
    assert client._m["jobs_expired"].value == 1
    master.join()

    # (c) the relay drops expired QUEUED jobs and re-stamps budgets
    relay = Relay(upstream="tcp://127.0.0.1:1", bind="tcp://127.0.0.1:*")
    now = time.monotonic()
    relay._children["c1"] = time.time()
    relay._jobq = [
        ({"job_id": 1, "job": {}, "_deadline_t": now - 1.0,
          "deadline_ms": 5000.0}, {"p": 1}),
        ({"job_id": 2, "job": {}, "_deadline_t": now + 5.0,
          "deadline_ms": 5000.0}, {"p": 1}),
    ]
    rep = relay._child_job({"cmd": "job", "count": 1, "id": "c1"}, "c1")
    assert rep["job_id"] == 2           # the expired job never served
    assert 0 < rep["deadline_ms"] <= 5000.0     # remaining budget
    assert relay.jobs_expired == 1
    assert relay.stats()["jobs_expired"] == 1


# -- byte-identity regression proof (guards PR 4/PR 5 compatibility) -----------

#: sha256 over the canonical update + job-reply frame stacks below —
#: the PORT (and anything after it) must not move a single wire byte.
#: Recompute ONLY for a deliberate, documented protocol revision.
_UPDATE_DIGEST = "5f691c603048a7201231598e62c7874d" \
                 "c974dfe8a46dde50d83d28a024aeaad7"
_JOB_DIGEST = "c02a608e0edd31679e03353735c5fc00" \
              "b26b48d8dcaf7ef8cd184cf3b62e6246"


def _canonical_update():
    rng = np.random.default_rng(7)
    return {"cmd": "update", "id": "s1", "job_id": 42,
            "step": 3, "trace_id": "t-42",
            "deltas": {"fc1": {"weights":
                               rng.standard_normal((8, 4))
                               .astype(np.float32),
                               "bias": rng.standard_normal(4)
                               .astype(np.float32)}},
            "metrics": {"loss": 0.5, "n_err": 3}}


def _canonical_job():
    rng = np.random.default_rng(8)
    return {"job_id": 42, "trace_id": "t-42", "train": True, "step": 3,
            "job": {"indices": np.arange(16, dtype=np.int64),
                    "class": 2, "size": 16, "last_minibatch": False,
                    "class_ended": False, "epoch_number": 0},
            "params": {"fc1": {"weights": rng.standard_normal((8, 4))
                               .astype(np.float32)}}}


def _frames_digest(frames):
    h = hashlib.sha256()
    for f in frames:
        b = bytes(f)
        h.update(len(b).to_bytes(8, "little"))
        h.update(b)
    return h.hexdigest()


def test_wire_frames_byte_identical_after_the_port():
    from znicz_tpu.parallel import wire

    up, _ = wire.encode_message(_canonical_update())
    job, _ = wire.encode_message(_canonical_job())
    assert _frames_digest(up) == _UPDATE_DIGEST
    assert _frames_digest(job) == _JOB_DIGEST
    # the Codec rides the same encoder: byte-identical frames
    codec = wire.Codec(owner="byte_identity")
    assert [bytes(f) for f in codec.encode(_canonical_update())] \
        == [bytes(f) for f in up]


#: the resume-snapshot contract (PR 2/PR 9/PR 11): these keys MUST
#: keep existing so pre-port snapshots restore and post-port snapshots
#: stay readable by the historical tooling
_RESUME_MASTER_KEYS = {
    "loader_pos", "hold", "outstanding", "job_seq", "jobs_by_slave",
    "lr_iteration", "apply_step", "decision_acc", "durations",
    "delta_norms", "counters"}
_RESUME_COUNTER_KEYS = {
    "jobs_done", "jobs_requeued", "stale_updates", "bad_updates",
    "bad_frames", "quarantined_updates", "reregistrations", "bytes_in",
    "bytes_out", "updates_received", "update_bytes_in", "prefetch_hit",
    "aggregated_updates", "stale_refused", "weighted_applies",
    "replans", "preemptions_ridden", "rate_limited_ingress",
    "tensor_bytes_raw_in", "tensor_bytes_wire_in",
    "tensor_bytes_raw_out", "tensor_bytes_wire_out"}


def test_resume_snapshot_and_status_names_unchanged(tmp_path):
    import json
    import urllib.request

    from znicz_tpu.server import Server
    from znicz_tpu.web_status import WebStatus

    wf = _make_workflow(tmp_path)
    srv = Server(wf, endpoint="tcp://127.0.0.1:0",
                 resume_path=str(tmp_path / "resume.pkl"))
    assert srv._handle({"cmd": "register", "id": "s1",
                        **_handshake_fields(wf)})["ok"]
    srv._handle({"cmd": "job", "id": "s1"})
    srv.save_resume(str(tmp_path / "resume.pkl"))
    with open(tmp_path / "resume.pkl", "rb") as f:
        snap = pickle.load(f)
    assert set(snap["master"].keys()) == _RESUME_MASTER_KEYS
    assert set(snap["master"]["counters"].keys()) == _RESUME_COUNTER_KEYS
    # a PRE-PORT snapshot (no post-port counter keys) still restores
    snap["master"]["counters"].pop("rate_limited_ingress")
    with open(tmp_path / "old.pkl", "wb") as f:
        pickle.dump(snap, f)
    srv2 = Server(wf, endpoint="tcp://127.0.0.1:0")
    srv2.restore_resume(str(tmp_path / "old.pkl"))
    assert srv2.resumed and srv2.rate_limited_ingress == 0
    # /status.json: every historical master counter name still there
    status = WebStatus(port=0).start()
    try:
        status.register(wf)
        status.register_server(srv)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/status.json") as r:
            master = json.load(r)["master"]
    finally:
        status.stop()
    for name in ("jobs_done", "jobs_requeued", "stale_updates",
                 "bytes_in", "bytes_out", "updates_received",
                 "update_bytes_in", "bytes_per_update",
                 "compression_ratio", "prefetch_hit", "bad_updates",
                 "bad_frames", "quarantined_updates",
                 "reregistrations", "resume_saves", "job_timeout_s",
                 "aggregated_updates", "rate_limited_ingress"):
        assert name in master, name
    for name in ("min_slaves", "members", "degraded", "apply_step",
                 "staleness_bound", "stale_refused", "replans",
                 "preemptions_ridden"):
        assert name in master["elastic"], name


# -- the cross-plane chaos soak ------------------------------------------------


def _expected_rep_faults(schedule, n):
    """What a REP plane's built-in hook must have counted after ``n``
    messages: the schedule's transport stream replayed, with ``drop``
    remapped to ``corrupt`` (lockstep sockets cannot drop)."""
    c = _Counter(schedule.decide_transport(i)[0] for i in range(n))
    return {"drop": 0, "corrupt": c["drop"] + c["corrupt"]}


def _expected_router_faults(schedule, n):
    c = _Counter(schedule.decide_transport(i)[0] for i in range(n))
    return {"drop": c["drop"], "corrupt": c["corrupt"]}


def _assert_plane_accounted(loop, schedule, rep: bool):
    """The soak's core claim: this plane's fault counters are EXACTLY
    the shared schedule's transport stream replayed over its message
    count — same seed, same core, every plane."""
    expect = (_expected_rep_faults if rep else
              _expected_router_faults)(schedule, loop.messages)
    assert loop.fault_counts() == expect


def _soak_training(tmp_path, schedule, n_slaves=1, max_epochs=2,
                   n_train=120):
    """master + relay + slaves, built-in chaos on BOTH REP planes."""
    from znicz_tpu.client import Client
    from znicz_tpu.parallel.relay import Relay
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m", max_epochs=max_epochs,
                               n_train=n_train)
    master_ep = f"tcp://127.0.0.1:{_free_port()}"
    srv = Server(master_wf, endpoint=master_ep, job_timeout=30.0)
    srv.transport_chaos = schedule
    srv_thread = threading.Thread(target=srv.serve,
                                  kwargs={"linger": 1.0}, daemon=True)
    srv_thread.start()
    relay = Relay(upstream=master_ep,
                  bind=f"tcp://127.0.0.1:{_free_port()}",
                  flush_s=0.05)
    relay.transport_chaos = schedule
    relay.start()
    slaves = [Client(_make_workflow(tmp_path / f"s{i}",
                                    max_epochs=max_epochs,
                                    n_train=n_train),
                     endpoint=relay.bind, slave_id=f"soak-s{i}")
              for i in range(n_slaves)]
    threads = [threading.Thread(
        target=s.run, kwargs=dict(poll_sleep=0.01, recv_timeout=3.0,
                                  max_reconnects=30), daemon=True)
        for s in slaves]
    for t in threads:
        t.start()
    srv_thread.join(120)
    assert not srv_thread.is_alive(), "master never finished under chaos"
    for t in threads:
        t.join(30)
    relay.stop()
    assert bool(srv.decision.complete)
    assert srv.jobs_done > 0
    _assert_plane_accounted(srv._transport, schedule, rep=True)
    _assert_plane_accounted(relay._transport, schedule, rep=True)
    # corrupted ingress surfaced through the planes' OWN refusal paths
    faults = srv._transport.fault_counts()["corrupt"] \
        + relay._transport.fault_counts()["corrupt"]
    refusals = srv.bad_frames + relay.bad_frames
    assert refusals == faults
    return srv, relay


def _soak_balancer(schedule, n_requests=16):
    """balancer + scripted replicas + client, built-in chaos on the
    balancer's ROUTER plane."""
    from znicz_tpu.parallel.chaos import ScriptedReplica
    from znicz_tpu.serving import InferenceClient, ReplicaBalancer

    bal = ReplicaBalancer(heartbeat_s=0.05, replica_ttl_s=1.0,
                          failover_timeout_s=0.5, hedge=False)
    bal.transport_chaos = schedule
    bal.start()
    reps = [ScriptedReplica(bal.endpoint, f"soak-r{i}",
                            boot_scale=2.0).start() for i in range(2)]
    cli = InferenceClient(bal.endpoint, timeout=30.0,
                          resend_after_s=0.4)
    try:
        deadline = time.time() + 10
        while bal.ready_count() < 2 and time.time() < deadline:
            time.sleep(0.02)
        assert bal.ready_count() == 2
        x = np.arange(4, dtype=np.float32)
        for _ in range(n_requests):
            y = np.asarray(cli.infer(x, timeout=30.0))
            assert np.array_equal(y.ravel(), x * 2.0)
        _assert_plane_accounted(bal._transport, schedule, rep=False)
        assert bal.ledger()["balanced"]
    finally:
        cli.close()
        bal.stop()
        for r in reps:
            r.kill()
    return bal


def test_cross_plane_chaos_soak_lean(tmp_path):
    """ONE FaultSchedule seed drives every plane's built-in fault hook
    — master, relay, serving frontend, balancer — and each plane's
    fault counters are exactly that schedule's transport stream
    replayed through the shared core, while every plane survives and
    completes its work (ISSUE 14 acceptance)."""
    from znicz_tpu.parallel.chaos import FaultSchedule
    from znicz_tpu.serving import InferenceClient, InferenceServer

    schedule = FaultSchedule(SEED, drop=0.04, corrupt=0.04)
    # training plane: master + relay (REP lockstep, drop->corrupt)
    _soak_training(tmp_path, schedule)
    # serving frontend (ROUTER): same seed, its own stream replay
    wf = _make_workflow(tmp_path / "serve")
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=2.0)
    srv.transport_chaos = schedule
    srv.start()
    cli = InferenceClient(srv.endpoint, timeout=30.0,
                          resend_after_s=0.4)
    try:
        x = np.zeros((2, 784), np.float32)
        y0 = cli.infer(x, timeout=30.0)
        for _ in range(10):
            assert np.array_equal(cli.infer(x, timeout=30.0), y0)
        _assert_plane_accounted(srv._transport, schedule, rep=False)
    finally:
        cli.close()
        srv.stop()
    # balancer plane (ROUTER): same seed again
    _soak_balancer(schedule)


@pytest.mark.slow
def test_cross_plane_chaos_soak_full(tmp_path):
    """The full soak: doubled fault rates, two slaves through the
    relay over a longer run, and heavier balancer traffic — all from
    ONE seed (the partition ride-through has its own dedicated proxy
    test above)."""
    from znicz_tpu.parallel.chaos import FaultSchedule

    schedule = FaultSchedule(SEED + 1, drop=0.08, corrupt=0.08)
    srv, relay = _soak_training(tmp_path, schedule, n_slaves=2,
                                max_epochs=3, n_train=300)
    assert srv.jobs_done >= 10
    _soak_balancer(schedule, n_requests=48)
