"""Multi-host DCN smoke test (SURVEY.md §5 "Distributed communication
backend"): two OS processes bring up jax.distributed over a local
coordinator, build a global mesh with znicz_tpu.parallel.mesh, and psum
across process boundaries — the collective result proves DCN wiring."""

import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""\
    import sys

    from znicz_tpu.virtdev import provision_cpu_devices

    # verify=False: the count check would initialize the backend, which
    # must not happen before jax.distributed.initialize
    provision_cpu_devices(1, verify=False)
    from znicz_tpu.parallel.mesh import (distributed_init, make_mesh,
                                         shard_map)

    pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    distributed_init(coordinator=f"127.0.0.1:{port}",
                     num_processes=n, process_id=pid)
    import numpy as np

    import jax
    from jax.sharding import PartitionSpec as P

    assert jax.process_count() == n, jax.process_count()
    d = len(jax.devices())                   # global across BOTH processes
    assert d > len(jax.local_devices()), "no cross-process devices visible"
    mesh = make_mesh(axes=("data",))         # all d global devices
    psum = shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                     in_specs=P("data"), out_specs=P())
    # every process passes the same [0..d) array; jit shards it over the
    # global mesh, so the psum crosses the process (DCN) boundary
    x = np.arange(float(d), dtype=np.float32)
    total = float(np.asarray(jax.jit(psum)(x))[0])
    assert total == sum(range(d)), (total, d)
    print(f"proc {pid} dcn_ok devices={d} procs={n}", flush=True)
""")


def test_two_process_dcn_psum(tmp_path):
    worker = tmp_path / "dcn_worker.py"
    worker.write_text(WORKER)
    with socket.socket() as s:                # free coordinator port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    n = 2
    env = dict(os.environ)                    # script dir != repo: put the
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(n), str(port)],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for pid in range(n)]
    outs = []
    try:
        for proc in procs:
            stdout, stderr = proc.communicate(timeout=180)
            assert proc.returncode == 0, stderr[-2000:]
            outs.append(stdout)
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
    for pid, out in enumerate(outs):
        assert f"proc {pid} dcn_ok" in out and f"procs={n}" in out, out
