"""Dynamic-batching inference serving layer (ISSUE 4): batcher policy
units, 0-ULP batched-vs-unbatched parity, bucket-ladder jit-cache
hygiene, the wire Codec extraction, snapshot inference-load, the
ChaosProxy soak, the web panel, and the --serve CLI.

Overload-safe serving (ISSUE 6): admission control (token-bucket rate
limits + DRR fair queueing, refusal policies), deadline propagation,
the client circuit breaker, zero-downtime snapshot rollover with
/healthz-/readyz, and the chaos stall/flood soaks (slow-marked)."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.config import root


def _tiny_mnist_wf(n_train=120, layers=None):
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = n_train
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    if layers is not None:
        root.mnist.layers = list(layers)
    try:
        wf = mnist.MnistWorkflow()
    finally:
        root.mnist.layers = [100, 10]
    wf.initialize(device=None)
    return wf


# -- batcher policy -----------------------------------------------------------


def test_bucket_ladder():
    from znicz_tpu.serving import BucketLadder

    lad = BucketLadder(32)
    assert lad.rungs == [1, 2, 4, 8, 16, 32]
    assert lad.bucket_for(1) == 1
    assert lad.bucket_for(3) == 4
    assert lad.bucket_for(32) == 32
    with pytest.raises(ValueError):
        lad.bucket_for(33)
    # non-power-of-two max_batch gets its own top rung
    assert BucketLadder(24).rungs == [1, 2, 4, 8, 16, 24]
    # explicit rungs must end at max_batch
    with pytest.raises(ValueError):
        BucketLadder(8, rungs=[1, 4])


def _req(n):
    from znicz_tpu.serving import Request

    return Request(np.zeros((n, 4), np.float32), n, req_id=n)


def test_batcher_coalesces_under_max_batch():
    from znicz_tpu.serving import DynamicBatcher

    b = DynamicBatcher(max_batch=8, max_delay_ms=50.0, queue_bound=100)
    for n in (3, 2, 2, 4):              # 3+2+2 fit; 4 would overflow
        assert b.submit(_req(n)) is None
    batch = b.next_batch(timeout=0.5)
    assert [r.n for r in batch] == [3, 2, 2]   # order preserved, 4 left
    assert b.queue_depth == 4
    batch2 = b.next_batch(timeout=0.5)
    assert [r.n for r in batch2] == [4]
    assert b.bucket_hits[8] == 1 and b.bucket_hits[4] == 1
    assert b.batched_rows == 11 and b.padded_rows == (8 - 7) + 0


def test_batcher_max_delay_flushes_partial():
    from znicz_tpu.serving import DynamicBatcher

    b = DynamicBatcher(max_batch=32, max_delay_ms=30.0, queue_bound=100)
    b.submit(_req(2))
    t0 = time.perf_counter()
    batch = b.next_batch(timeout=1.0)
    waited = time.perf_counter() - t0
    assert [r.n for r in batch] == [2]
    assert 0.02 <= waited < 0.5          # the window, not the timeout
    # wait_fill=False takes only what is queued, instantly
    b.submit(_req(1))
    t0 = time.perf_counter()
    batch = b.next_batch(timeout=1.0, wait_fill=False)
    assert [r.n for r in batch] == [1]
    assert time.perf_counter() - t0 < 0.02


def test_batcher_backpressure_sheds_at_bound():
    from znicz_tpu.serving import DynamicBatcher

    b = DynamicBatcher(max_batch=4, max_delay_ms=1.0, queue_bound=10)
    for _ in range(5):
        assert b.submit(_req(2)) is None
    reason = b.submit(_req(2))           # 12 rows would exceed 10
    assert reason is not None and "shed" in reason
    assert b.shed == 1
    # oversized is refused outright, never queued
    reason = b.submit(_req(5))
    assert reason is not None and "max_batch" in reason
    assert b.oversized == 1
    assert b.queue_depth == 10


# -- admission control (ISSUE 6) ----------------------------------------------


def test_token_bucket_and_refusal_objects():
    from znicz_tpu.serving import Refusal, TokenBucket

    tb = TokenBucket(rate=100.0, burst=10.0)
    assert tb.try_take(10)                # the whole burst at once
    assert not tb.try_take(1)             # empty until refill
    time.sleep(0.06)                      # ~6 tokens refill
    assert tb.try_take(4)
    # refund caps at burst: a shed elsewhere must not mint tokens
    tb.refund(1000)
    assert tb.tokens == tb.burst and tb.is_full(time.perf_counter())
    # a Refusal IS the readable reason string, plus the policy slug
    r = Refusal("rate_limited", "client over its rate limit")
    assert isinstance(r, str) and "rate limit" in r
    assert r.policy == "rate_limited"
    assert str(r) == "client over its rate limit"   # plain str on the wire
    assert r.scope == "service"           # default; per-client limits
    assert Refusal("shed", "x", scope="client").scope == "client"


def _creq(n, client):
    from znicz_tpu.serving import Request

    return Request(np.zeros((n, 4), np.float32), n, req_id=n,
                   client=client)


def test_batcher_rate_limit_per_client():
    from znicz_tpu.serving import AdmissionPolicy, DynamicBatcher

    b = DynamicBatcher(max_batch=4, max_delay_ms=1.0, queue_bound=100,
                       admission=AdmissionPolicy(rate_limit=8.0,
                                                 rate_burst=8.0))
    for _ in range(4):
        assert b.submit(_creq(2, "a")) is None
    ref = b.submit(_creq(2, "a"))         # client a's burst is spent
    assert ref is not None and ref.policy == "rate_limited"
    assert "rate limit" in ref
    assert b.rate_limited == 1
    # one flooding client degrades only itself: b is untouched
    assert b.submit(_creq(2, "b")) is None
    assert b.clients["a"]["rate_limited"] == 1
    assert b.clients["b"]["accepted"] == 1
    st = b.admission_stats()
    assert st["rate_limit_rows_per_s"] == 8.0 and st["enabled"]

    # a shed refunds the tokens it took: client c's budget survives a
    # full queue, so it is NOT rate_limited once the queue drains
    b.queue_bound = 0
    for _ in range(4):
        ref = b.submit(_creq(2, "c"))
        assert ref is not None and ref.policy == "shed"
    b.queue_bound = 100
    for _ in range(4):                    # the whole burst still there
        assert b.submit(_creq(2, "c")) is None

    # the bucket table is bounded (the transport core's AdmissionTable
    # since ISSUE 14): idle (refilled) buckets are swept once max_peers
    # distinct client ids have been seen
    b._table._buckets.clear()
    b._table.max_peers = 8
    for i in range(40):
        b.submit(_creq(1, f"eph-{i}"))
    assert len(b._table) <= 8


def test_batcher_drr_interleaves_clients_and_bounds_one():
    from znicz_tpu.serving import AdmissionPolicy, DynamicBatcher

    b = DynamicBatcher(max_batch=4, max_delay_ms=1.0, queue_bound=1000,
                       admission=AdmissionPolicy(fair=True, quantum=1))
    for _ in range(12):
        assert b.submit(_creq(1, "flood")) is None
    for _ in range(2):
        assert b.submit(_creq(1, "good")) is None
    batch = b.next_batch(timeout=0.1, wait_fill=False)
    # deficit round robin: the good client's rows ride the FIRST batch,
    # interleaved — never parked behind the flooder's backlog
    assert [r.client for r in batch] == ["flood", "good", "flood", "good"]
    # the flooder alone still fills whole batches (single-queue FIFO)
    batch = b.next_batch(timeout=0.1, wait_fill=False)
    assert [r.client for r in batch] == ["flood"] * 4

    # per-client queue bound: one client cannot monopolize queue_bound
    b2 = DynamicBatcher(max_batch=4, max_delay_ms=1.0, queue_bound=100,
                        admission=AdmissionPolicy(fair=True,
                                                  client_queue_bound=4))
    for _ in range(4):
        assert b2.submit(_creq(1, "hog")) is None
    ref = b2.submit(_creq(1, "hog"))
    assert ref is not None and ref.policy == "shed" \
        and "fair-share" in ref
    # the hog's OWN bound refused it — client-scoped, so its breaker
    # must not count it against the (healthy) service
    assert ref.scope == "client"
    assert b2.submit(_creq(1, "other")) is None


def test_batcher_admission_toggle_mid_traffic():
    """set_admission(off) after fair traffic drained (the bench's
    on/off overhead toggle): the retired per-client queue coexists with
    the shared (None-keyed) FIFO and the drain must still make progress
    — regression for the _visiting sentinel colliding with the shared
    queue's None key (an infinite DRR loop under the queue lock)."""
    from znicz_tpu.serving import AdmissionPolicy, DynamicBatcher

    b = DynamicBatcher(max_batch=4, max_delay_ms=1.0, queue_bound=100,
                       admission=AdmissionPolicy(fair=True))
    assert b.submit(_creq(1, "a")) is None
    assert [r.client for r in b.next_batch(0.1, wait_fill=False)] == ["a"]
    b.set_admission(AdmissionPolicy(enabled=False))
    assert b.submit(_creq(1, "a")) is None    # shared FIFO now
    batch = b.next_batch(0.1, wait_fill=False)
    assert batch is not None and len(batch) == 1
    # and back on: per-client queues resume next to the shared leftover
    b.set_admission(AdmissionPolicy(fair=True))
    assert b.submit(_creq(1, "b")) is None
    assert b.submit(_creq(1, "c")) is None
    taken = []
    while True:
        nb = b.next_batch(0.05, wait_fill=False)
        if nb is None:
            break
        taken += [r.client for r in nb]
    assert sorted(taken) == ["b", "c"]


# -- codec extraction (ISSUE 4 satellite) -------------------------------------


def test_codec_frames_byte_identical_and_counted():
    from znicz_tpu.parallel import wire

    msg = {"cmd": "infer", "req_id": 7,
           "x": np.arange(12, dtype=np.float32).reshape(3, 4)}
    bare, info = wire.encode_message(msg)
    codec = wire.Codec()
    framed = codec.encode(msg)
    assert [bytes(f) for f in framed] == [bytes(f) for f in bare]
    assert codec.bytes_out == sum(len(bytes(f)) for f in bare)
    assert codec.tensor_bytes_wire_out == info["wire_bytes"]
    dec, dinfo = codec.decode([bytes(f) for f in framed])
    assert np.array_equal(dec["x"], msg["x"])
    assert codec.bytes_in == codec.bytes_out
    assert dinfo["message_bytes"] == codec.bytes_in
    assert codec.compression_ratio("in") == pytest.approx(1.0)
    # refusal: counted, legacy-framed (single pickle any peer can
    # read), slug + wording from the transport core (ISSUE 14)
    frames = codec.refusal("torn")
    assert codec.bad_frames == 1
    import pickle

    rep = pickle.loads(frames[0])
    assert rep["bad_frame"] and rep["error"] == "bad frame: torn"


def test_server_counters_ride_the_codec(tmp_path):
    """The Server's historical counter names read/write its Codec (the
    resume snapshot setattr's them by name)."""
    from znicz_tpu.server import Server

    wf = _tiny_mnist_wf()
    srv = Server(wf, endpoint="tcp://127.0.0.1:17579")
    srv.bytes_in = 123
    assert srv.codec.bytes_in == 123
    srv.bad_frames += 1
    assert srv.codec.bad_frames == 1
    srv.codec.tensor_bytes_raw_in = 40
    srv.codec.tensor_bytes_wire_in = 10
    assert srv.compression_ratio("in") == pytest.approx(4.0)


# -- model runner: parity + jit-cache hygiene ---------------------------------


def test_batched_vs_unbatched_parity_0ulp_and_padding_masked():
    """The dynamic-batching correctness contract, to 0 ULP: a request's
    result rows are a pure function of ITS rows and the bucket
    executable — independent of what it was coalesced with, its offset
    inside the batch, and the pad content.  (Parity is per BUCKET: XLA
    compiles a different executable per batch shape, and e.g. the
    batch-1 gemv path legitimately differs from the gemm path in final
    bits — which is exactly why the ladder pins the executable set.)"""
    from znicz_tpu.serving import ModelRunner

    wf = _tiny_mnist_wf()
    runner = ModelRunner(wf)
    rng = np.random.default_rng(7)
    xs = [rng.normal(0, 1, (n, 784)).astype(np.float32)
          for n in (1, 4, 3)]            # 8 rows: one bucket-8 batch
    # unbatched reference: each request served ALONE in bucket 8
    alone = [runner.infer(runner.pad(x, 8))[:len(x)] for x in xs]
    # coalesced: all three share one bucket-8 batch
    batched = runner.infer(np.concatenate(xs, axis=0))
    off = 0
    for x, ref in zip(xs, alone):
        assert np.array_equal(batched[off:off + len(x)], ref)
        off += len(x)
    # padding is masked out of results AND cannot leak in: garbage pad
    # rows leave the real rows bit-identical
    garbage = runner.pad(xs[2], 8)
    garbage[3:] = 1e9
    assert np.array_equal(runner.infer(garbage)[:3], alone[2])


def test_warmup_compiles_ladder_then_zero_recompiles():
    from znicz_tpu.serving import BucketLadder, ModelRunner

    wf = _tiny_mnist_wf()
    runner = ModelRunner(wf)
    ladder = BucketLadder(8)
    n = runner.warmup(ladder)
    assert n == len(ladder.rungs)
    if runner.jit_cache_size() is not None:
        assert runner.jit_cache_size() == n
    for rows in (1, 3, 7, 8, 2, 5, 4, 6):
        runner.infer(np.zeros((ladder.bucket_for(rows),) + (784,),
                              np.float32))
    assert runner.compiles == n          # every bucket was a cache hit


# -- snapshot inference-load path ---------------------------------------------


def test_snapshot_inference_load(tmp_path):
    from znicz_tpu import snapshotter
    from znicz_tpu.serving import ModelRunner

    wf = _tiny_mnist_wf()
    wf.snapshotter.directory = str(tmp_path)   # before run(): the
    # on-improvement save must not land in the repo's snapshots/
    root.mnist.decision.max_epochs = 1
    try:
        wf.run()
    finally:
        root.mnist.decision.max_epochs = 5
    path = wf.snapshotter.save("serve_test")
    trained = {f.name: {k: np.array(a.map_read())
                        for k, a in f.params().items()}
               for f in wf.forwards}

    fresh = _tiny_mnist_wf()
    meta = snapshotter.load_inference(fresh, path)
    assert "units" not in meta and "epoch" in meta
    for f in fresh.forwards:
        for k, a in f.params().items():
            np.testing.assert_array_equal(np.array(a.map_read()),
                                          trained[f.name][k])
    # the served forward IS the trained function
    runner = ModelRunner(fresh)
    x = np.asarray(wf.loader.original_data.mem[:5], np.float32)
    y = runner.infer(x)
    assert y.shape == (5, 10) and np.all(np.isfinite(y))

    # a snapshot that does not cover the model's weighted layers is
    # refused, not silently half-served
    with pytest.raises(ValueError, match="no params"):
        snapshotter.restore_inference(fresh, {"units": {"fwd0": {}}})


# -- end-to-end service -------------------------------------------------------


def test_e2e_mixed_sizes_parity_and_stats():
    from znicz_tpu.serving import (InferenceClient, InferenceError,
                                   InferenceServer)

    wf = _tiny_mnist_wf()
    srv = InferenceServer(wf, max_batch=8, max_delay_ms=3.0,
                          queue_bound=64).start()
    cli = InferenceClient(srv.endpoint, timeout=30)
    try:
        compiles_warm = srv.runner.compiles
        ladder = srv.batcher.ladder
        rng = np.random.default_rng(11)
        for n in (1, 3, 8, 2, 5, 1, 7, 4):
            x = rng.normal(0, 1, (n, 784)).astype(np.float32)
            y = cli.infer(x)
            # 0 ULP e2e vs the request served directly at its bucket
            ref = srv.runner.infer(
                srv.runner.pad(x, ladder.bucket_for(n)))[:n]
            assert np.array_equal(y, ref)
        # a bare sample (no batch axis) is accepted
        y = cli.infer(rng.normal(0, 1, (784,)).astype(np.float32))
        assert y.shape == (1, 10)
        assert srv.runner.compiles == compiles_warm   # zero recompiles
        # oversized requests are refused with the reason, not dropped
        with pytest.raises(InferenceError, match="max_batch"):
            cli.infer(np.zeros((9, 784), np.float32))
        # wrong sample shape is refused readably
        with pytest.raises(InferenceError, match="sample shape"):
            cli.infer(np.zeros((2, 77), np.float32))
        # control commands + the stats payload the web panel shows
        assert cli.ping()["pong"]
        stats = cli.stats()
        assert stats["served"] >= 9 and stats["rejected"] >= 1
        assert stats["p50_ms"] is not None
        assert sum(stats["batcher"]["bucket_hits"].values()) \
            == stats["batcher"]["batches"]
        assert stats["model"]["compiles"] == compiles_warm
    finally:
        cli.close()
        srv.stop()


def test_start_surfaces_real_bind_error():
    """start() re-raises the serve thread's actual failure (bind
    conflict here) instead of hanging out a timeout and masking it."""
    from znicz_tpu.serving import InferenceServer

    wf = _tiny_mnist_wf()
    srv = InferenceServer(wf, max_batch=2, max_delay_ms=1.0).start()
    try:
        with pytest.raises(RuntimeError, match="failed on"):
            InferenceServer(wf, bind=srv.endpoint, max_batch=2,
                            max_delay_ms=1.0).start()
    finally:
        srv.stop()


def test_e2e_undecodable_frames_refused_not_fatal():
    """A garbage request is refused with a counted error reply and the
    service keeps serving — the master's bad-frame fault model extends
    to serving."""
    import zmq

    from znicz_tpu.serving import InferenceClient, InferenceServer

    wf = _tiny_mnist_wf()
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=2.0).start()
    ctx = zmq.Context.instance()
    raw = ctx.socket(zmq.DEALER)
    raw.setsockopt(zmq.RCVTIMEO, 10_000)
    raw.setsockopt(zmq.LINGER, 0)
    raw.connect(srv.endpoint)
    cli = InferenceClient(srv.endpoint, timeout=30)
    try:
        from znicz_tpu.parallel import wire

        raw.send_multipart([b"\xff garbage \x00"])
        rep, _ = wire.decode_message(raw.recv_multipart())
        assert rep["bad_frame"] is True
        assert srv.bad_frames == 1
        # the service still answers real requests afterwards
        y = cli.infer(np.zeros((2, 784), np.float32))
        assert y.shape == (2, 10)
    finally:
        raw.close(0)
        cli.close()
        srv.stop()


def test_chaos_soak_serving():
    """Multi-client soak through the seeded ChaosProxy: dropped and
    corrupted frames in BOTH directions, duplicated and delayed
    messages — every request still completes with bit-exact results
    (resend + req_id dedup), the server never crashes, and every
    corrupted request-direction message is accounted in ``bad_frames``
    exactly like the master's fault model."""
    from znicz_tpu.parallel.chaos import ChaosProxy, FaultSchedule
    from znicz_tpu.serving import InferenceClient, InferenceServer

    wf = _tiny_mnist_wf()
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=2.0,
                          queue_bound=64).start()
    proxy = ChaosProxy("tcp://127.0.0.1:17591", srv.endpoint,
                       FaultSchedule(2024, drop=0.05, corrupt=0.06,
                                     duplicate=0.04, delay=0.05,
                                     delay_s=(0.01, 0.05))).start()
    errs = []
    rng = np.random.default_rng(5)
    payloads = [rng.normal(0, 1, (1 + i % 4, 784)).astype(np.float32)
                for i in range(12)]
    expected = [None] * len(payloads)

    def worker(wid):
        cli = InferenceClient("tcp://127.0.0.1:17591", timeout=60,
                              resend_after_s=0.3)
        try:
            for i in range(wid, len(payloads), 3):
                y = cli.infer(payloads[i])
                expected[i] = y
        except Exception as exc:        # pragma: no cover - failure path
            errs.append((wid, exc))
        finally:
            cli.close()

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    try:
        assert not errs, errs
        assert all(e is not None for e in expected)
        # bit-exact through the chaos: concurrent clients coalesce, so
        # a request may have been served under ANY rung >= its rows —
        # its bits must match that rung's executable exactly (pure
        # function of own rows + bucket; zero cross-request leakage)
        ladder = srv.batcher.ladder
        for i, x in enumerate(payloads):
            refs = [srv.runner.infer(srv.runner.pad(x, b))[:len(x)]
                    for b in ladder.rungs if b >= len(x)]
            assert any(np.array_equal(expected[i], ref)
                       for ref in refs), i
        # accounting: every corrupted request-direction message the
        # proxy injected was refused and counted by the server
        assert srv.bad_frames == proxy.counters["req"]["corrupt"]
        if proxy.counters["req"]["corrupt"]:
            assert srv.bad_frames > 0
        assert srv.served >= len(payloads)
    finally:
        proxy.stop()
        srv.stop()


# -- circuit breaker (ISSUE 6) ------------------------------------------------


def _fake_ok_service(endpoint, stop_evt, ready_evt):
    """A model-free ROUTER peer answering every infer with ok+y — the
    breaker's 'service came back' half, without paying a jit warmup."""
    import zmq

    from znicz_tpu.parallel import wire

    ctx = zmq.Context.instance()
    sock = ctx.socket(zmq.ROUTER)
    sock.setsockopt(zmq.LINGER, 0)
    sock.bind(endpoint)
    ready_evt.set()
    try:
        while not stop_evt.is_set():
            if not sock.poll(20):
                continue
            frames = sock.recv_multipart()
            envelope, payload = wire.split_envelope(frames)
            req, _ = wire.decode_message(payload)
            rep = {"ok": True, "req_id": req.get("req_id"), "gen": 1,
                   "y": np.zeros((1, 2), np.float32)}
            sock.send_multipart(list(envelope)
                                + wire.encode_message(rep)[0])
    finally:
        sock.close(0)


def test_circuit_breaker_opens_backs_off_and_recovers():
    from znicz_tpu.serving import CircuitOpenError, InferenceClient

    endpoint = "tcp://127.0.0.1:17593"    # nothing listening yet
    cli = InferenceClient(endpoint, timeout=5, resend_after_s=0.05,
                          max_resends=1, breaker_window=4,
                          breaker_failures=2, breaker_reset_s=0.3)
    x = np.zeros((1, 4), np.float32)
    stop_evt = threading.Event()
    ready_evt = threading.Event()
    t = None
    try:
        # the capped resend loop gives up readably and counts it
        # (ISSUE 6 satellite: max_resends mirrors connect_retries)
        for _ in range(2):
            with pytest.raises(TimeoutError, match="giving up"):
                cli.infer(x)
        assert cli.give_ups == 2
        # two failures in the window >= threshold: breaker OPEN, the
        # next submit fails fast LOCALLY
        assert cli.breaker_state == "open"
        assert cli.breaker_opens == 1
        with pytest.raises(CircuitOpenError, match="circuit open"):
            cli.submit(x)
        assert cli.breaker_short_circuits == 1
        # service comes back; after the backoff ONE probe goes through
        t = threading.Thread(target=_fake_ok_service,
                             args=(endpoint, stop_evt, ready_evt),
                             daemon=True)
        t.start()
        assert ready_evt.wait(10)
        time.sleep(0.35)                  # past breaker_reset_s
        rid = cli.submit(x)               # the half-open probe
        assert cli.breaker_state == "half_open"
        assert cli.breaker_probes == 1
        with pytest.raises(CircuitOpenError, match="half-open"):
            cli.submit(x)                 # only ONE probe in flight
        rep = cli.result(rid, timeout=10)
        assert rep["ok"]
        assert cli.breaker_state == "closed"   # probe success closes it
        cli.infer(x, timeout=10)          # and traffic flows again
    finally:
        stop_evt.set()
        if t is not None:
            t.join(timeout=10)
        cli.close()


# -- fairness + refusal-policy propagation (ISSUE 6) --------------------------


def _good_window(clis, x1, duration, pace_hz):
    """Paced sequential windows for N well-behaved clients (each offers
    ``pace_hz`` req/s — under its rate limit, as a well-behaved tenant
    does); returns (accepted requests/s across all, p99 ms)."""
    lats = []
    errs = []

    def drive(cli):
        interval = 1.0 / pace_hz
        t_end = time.perf_counter() + duration
        nxt = time.perf_counter()
        while True:
            now = time.perf_counter()
            if now >= t_end:
                return
            if now < nxt:
                time.sleep(min(nxt - now, 0.005))
                continue
            # no catch-up bursts after a slow reply: a real paced
            # client skips ticks, it does not hammer
            nxt = max(nxt + interval, now)
            t0 = time.perf_counter()
            try:
                cli.infer(x1)
            except Exception as exc:      # pragma: no cover - failure path
                errs.append(exc)
                return
            lats.append(time.perf_counter() - t0)

    threads = [threading.Thread(target=drive, args=(c,)) for c in clis]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    return (len(lats) / duration,
            float(np.percentile(np.asarray(lats) * 1e3, 99)))


def _band_pair(fl_t, base_t, fl_p, base_p):
    """True iff ONE flood/no-flood pair clears BOTH fairness bands."""
    return any(ft / bt >= 0.8 and fp / bp <= 1.2
               for ft, bt, fp, bp in zip(fl_t, base_t, fl_p, base_p))


def _run_fairness(srv, rate, n_good, window_s, rounds, factor=10.0,
                  flood_rows=1):
    """Interleaved no-flood/flood windows (PR-4 best-of discipline: a
    host load spike only ever slows a window, and it hits both
    variants); asserts the 20% fairness band and the flooder's
    refusal-policy purity.  The flooder runs in its OWN process
    (chaos.FloodProcess): a real misbehaving tenant shares no GIL with
    the service, while an in-process flood thread would bill its own
    Python overhead onto every good-client latency sample on this
    1-core container."""
    import sys

    from znicz_tpu.parallel.chaos import FloodProcess
    from znicz_tpu.serving import InferenceClient

    x1 = np.zeros((1, 784), np.float32)
    pace_hz = rate / 2                    # each good client offers half
    # its own rate limit — well-behaved by construction
    clis = [InferenceClient(srv.endpoint, timeout=60)
            for _ in range(n_good)]
    base_t, base_p, fl_t, fl_p = [], [], [], []
    stats = {}
    flood = FloodProcess(srv.endpoint, 784, rate, factor=factor,
                         rows=flood_rows)
    switch = sys.getswitchinterval()
    sys.setswitchinterval(1e-3)           # bench discipline: don't let
    # 5ms GIL slices dominate the p99 of a multi-thread window
    try:
        _good_window(clis, x1, 0.3, pace_hz)      # warm the path
        for _ in range(rounds):
            t, p = _good_window(clis, x1, window_s, pace_hz)
            base_t.append(t)
            base_p.append(p)
            flood.start_flood()
            time.sleep(0.15)              # flood reaches steady state
            t, p = _good_window(clis, x1, window_s, pace_hz)
            fl_t.append(t)
            fl_p.append(p)
            stats = flood.stop_flood()
            # the flooder is refused by exactly ONE policy: its own
            # rate limit — never shed/deadline collateral
            assert stats["refusals"].get("rate_limited", 0) > 0, stats
            assert set(stats["refusals"]) == {"rate_limited"}, stats
            assert stats["accepted"] > 0  # its fair share still served
            if _band_pair(fl_t, base_t, fl_p, base_p):
                break                     # band met; stop burning time
    finally:
        sys.setswitchinterval(switch)
        flood.close()
        for c in clis:
            c.close()
    # best-of PAIRS (PR-4 discipline): each flood window is judged
    # against its ADJACENT no-flood window, so a cgroup/load phase hits
    # both sides of a ratio; comparing global min-vs-min across rounds
    # measured minutes apart just measures the host's swing.  A
    # structurally unfair service fails EVERY pair; one clean-phase
    # pair inside BOTH bands clears it: well-behaved clients keep
    # >= 80% of their paired no-flood throughput AND p99 within 20%,
    # in the SAME pair (a service unfair in alternating ways must not
    # pass by mixing one pair's throughput with another pair's p99).
    assert _band_pair(fl_t, base_t, fl_p, base_p), \
        (base_t, fl_t, base_p, fl_p)
    return stats


def test_fairness_under_flood_and_refusal_policies_lean():
    from znicz_tpu.serving import (AdmissionPolicy, InferenceClient,
                                   InferenceError, InferenceServer)

    wf = _tiny_mnist_wf()
    rate = 20.0                           # rows/s per client — the
    # flood offers 200 rows/s as 8-row requests (25 msg/s): the bucket
    # meters ROWS, so this is the same 10x overload, but the lean test
    # must fit this 1-core container — at 200 one-row msg/s the flood
    # process's scheduler quanta alone push good-client p99 2-10x out
    # of band (CPU itself is not a resource admission control can
    # ration; the flood's WORK must fit the host).  The slow soak keeps
    # the per-message variant.  burst=8 so an 8-row request is ever
    # admittable (accepted>0 asserts the fair share is still served).
    srv = InferenceServer(
        wf, max_batch=8, max_delay_ms=2.0, queue_bound=64,
        admission=AdmissionPolicy(rate_limit=rate,
                                  rate_burst=8.0)).start()
    try:
        # 6 best-of rounds with early exit (usually 1-2 run): this
        # box's cgroup share swings 4x minute-to-minute, and a 3-round
        # run can land entirely inside one bad phase
        _run_fairness(srv, rate, n_good=2, window_s=2.0, rounds=6,
                      flood_rows=8)

        # refusal-policy propagation: every refusal reply NAMES the
        # policy that refused it
        cli = InferenceClient(srv.endpoint, timeout=30)
        try:
            with pytest.raises(InferenceError) as ei:
                cli.infer(np.zeros((9, 784), np.float32))
            assert ei.value.reply["policy"] == "oversized"
            with pytest.raises(InferenceError) as ei:
                cli.infer(np.zeros((1, 784), np.float32),
                          deadline_s=1e-6)
            rep = ei.value.reply
            assert rep["policy"] == "deadline" and rep["timed_out"]
            srv.batcher.queue_bound = 0   # squeeze: everything sheds
            try:
                with pytest.raises(InferenceError) as ei:
                    cli.infer(np.zeros((1, 784), np.float32))
            finally:
                srv.batcher.queue_bound = 64
            assert ei.value.reply["policy"] == "shed"
            # a GLOBAL shed is service-scoped on the wire (the breaker
            # counts it); per-client refusals say scope=client
            assert ei.value.reply["scope"] == "service"
            # the panel's per-client admission table saw the flooder
            adm = cli.stats()["batcher"]["admission"]
            assert adm["clients"]["flooder"]["rate_limited"] > 0
            assert srv.batcher.rate_limited > 0
            assert srv.stats()["rejected"] > 0
        finally:
            cli.close()
    finally:
        srv.stop()


@pytest.mark.slow
def test_fairness_soak_full():
    """The full fairness proof: longer interleaved windows, 3
    well-behaved clients, flood at 10x the rate limit.  More best-of
    rounds than the lean version: with three paced client threads the
    per-window p99 rides on ~10 GIL handoffs per request, and this
    container's cgroup share swings 4x minute-to-minute — early-exit
    keeps the usual cost at one or two rounds."""
    from znicz_tpu.serving import AdmissionPolicy, InferenceServer

    wf = _tiny_mnist_wf()
    rate = 20.0
    srv = InferenceServer(
        wf, max_batch=8, max_delay_ms=2.0, queue_bound=128,
        admission=AdmissionPolicy(rate_limit=rate,
                                  rate_burst=rate / 4)).start()
    try:
        _run_fairness(srv, rate, n_good=3, window_s=4.0, rounds=8)
    finally:
        srv.stop()


# -- zero-downtime snapshot rollover (ISSUE 6) --------------------------------


def _perturbed_snapshot(wf, tmp_path, tag="gen2"):
    """Nudge every forward param and save — a second snapshot whose
    outputs are bit-distinguishable from the served generation's."""
    wf.snapshotter.directory = str(tmp_path)
    for f in wf.forwards:
        for k, a in f.params().items():
            a.mem = np.asarray(a.map_read()) * np.float32(1.25) \
                + np.float32(0.01)
    return wf.snapshotter.save(tag)


def _gen_refs(srv, x1):
    """Per-rung reference outputs of the CURRENT generation for one
    row (any rung a coalesced request may ride)."""
    return {b: srv.runner.infer(srv.runner.pad(x1, b))[:1]
            for b in srv.batcher.ladder.rungs}


def test_rollover_under_load_readiness_and_health(tmp_path):
    import urllib.error

    from znicz_tpu.parallel.chaos import FaultSchedule
    from znicz_tpu.serving import InferenceClient, InferenceServer
    from znicz_tpu.web_status import WebStatus

    wf = _tiny_mnist_wf()
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=1.0,
                          queue_bound=64).start()
    status = WebStatus(port=0).start()
    status.register_inference(srv)
    rng = np.random.default_rng(31)
    x1 = rng.normal(0, 1, (1, 784)).astype(np.float32)
    results = []
    errs = []
    stop = threading.Event()
    loader = None
    try:
        ref_a = _gen_refs(srv, x1)        # generation-1 oracle, per rung
        path_b = _perturbed_snapshot(wf, tmp_path)
        assert srv.ready() and srv.alive()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/readyz") as r:
            assert r.status == 200 and json.load(r)["ready"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/healthz") as r:
            assert r.status == 200 and json.load(r)["ok"]
        # 100%-probability stalls (the new chaos kind) slow every
        # dispatch AND the swap's bucket warm, so the not-ready window
        # is wide enough to observe deterministically
        srv.runner.inject_compute_faults(
            FaultSchedule(99, stall=1.0, stall_s=(0.02, 0.02)))

        def load():
            cli = InferenceClient(srv.endpoint, timeout=60)
            try:
                while not stop.is_set():
                    rep = cli.result(cli.submit(x1))
                    results.append((rep["gen"], rep["y"]))
            except Exception as exc:      # pragma: no cover - failure
                errs.append(exc)
            finally:
                cli.close()

        loader = threading.Thread(target=load)
        loader.start()
        t0 = time.perf_counter()
        while len(results) < 3 and not errs:      # gen-1 replies exist
            assert time.perf_counter() - t0 < 30
            time.sleep(0.005)
        cli2 = InferenceClient(srv.endpoint, timeout=30)
        try:
            rep = cli2.swap(path_b)       # the wire rollover trigger
            assert rep["ok"] and rep["swap_started"]
            assert rep["generation"] == 1     # still serving gen 1
            saw_warming = False
            t0 = time.perf_counter()
            while srv.runner.generation == 1:
                if srv.runner.swapping and not srv.ready():
                    saw_warming = True    # /readyz false DURING warm
                assert time.perf_counter() - t0 < 60
                time.sleep(0.001)
            assert saw_warming
            t0 = time.perf_counter()
            while not srv.ready():        # and true again after
                assert time.perf_counter() - t0 < 30
                time.sleep(0.002)
            n_now = len(results)
            t0 = time.perf_counter()
            while len(results) < n_now + 3 and not errs:  # gen-2 traffic
                assert time.perf_counter() - t0 < 30
                time.sleep(0.005)
        finally:
            cli2.close()
        stop.set()
        loader.join(timeout=60)
        assert not errs, errs
        # ZERO accepted requests lost: the sync load loop got an ok
        # reply for every submit, and the server's accounting agrees
        assert srv.served == len(results)
        assert srv.timed_out == 0 and srv.rejected == 0
        # never a mixed-generation answer: every reply's rows are
        # bit-exact under exactly its stamped generation's params (at
        # whatever rung it rode), and generations flip once, in order
        srv.runner.inject_compute_faults(FaultSchedule(99, stall=0.0))
        ref_b = _gen_refs(srv, x1)
        # the proof is non-vacuous: the two generations really answer
        # differently (else "bit-exact under its gen" proves nothing)
        assert not np.array_equal(ref_a[1], ref_b[1])
        gens = [g for g, _ in results]
        assert gens == sorted(gens) and gens[0] == 1 and gens[-1] == 2
        assert set(gens) == {1, 2}
        for g, y in results:
            refs = ref_a if g == 1 else ref_b
            assert any(np.array_equal(y, r) for r in refs.values()), g
        assert srv.runner.swaps == 1
        assert srv.runner._m_stalls.value > 0     # the stall kind fired
        # swap refusals keep the live generation: an empty path is
        # refused inline, a missing file fails in the background with
        # no flip, and the service keeps answering
        cli3 = InferenceClient(srv.endpoint, timeout=30)
        try:
            from znicz_tpu.serving import InferenceError

            with pytest.raises(InferenceError, match="path"):
                cli3.swap("")
            rep = cli3.swap(str(tmp_path / "missing.pkl.gz"))
            assert rep["swap_started"]
            t0 = time.perf_counter()
            while srv.runner.swap_failures == 0:
                assert time.perf_counter() - t0 < 30
                time.sleep(0.01)
            assert srv.runner.generation == 2     # unchanged
            assert cli3.infer(x1).shape == (1, 10)
        finally:
            cli3.close()
        # draining: stop() flips /readyz to 503 with the reason
        srv.stop()
        with pytest.raises(urllib.error.HTTPError) as he:
            urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/readyz")
        assert he.value.code == 503
        assert json.loads(he.value.read())["reason"] == "draining"
    finally:
        stop.set()
        if loader is not None:
            loader.join(timeout=10)
        status.stop()
        srv.stop()


@pytest.mark.slow
def test_chaos_soak_rollover_flood_stall(tmp_path):
    """The ISSUE 6 soak: snapshot swap + one flooding client + seeded
    compute stalls + drop/corrupt/dup/delay network faults, all
    concurrently.  Every accepted request's result is bit-exact under
    its stamped generation; every flooder refusal is rate_limited;
    every proxy-corrupted request is accounted in bad_frames."""
    from znicz_tpu.parallel.chaos import (ChaosProxy, FaultSchedule,
                                          FloodDriver)
    from znicz_tpu.serving import (AdmissionPolicy, InferenceClient,
                                   InferenceServer)

    wf = _tiny_mnist_wf()
    rate = 30.0                           # modest: the single-threaded
    # proxy relays flood + good traffic on one shared core, and this
    # soak asserts accounting/bit-exactness, not latency bands
    srv = InferenceServer(
        wf, max_batch=4, max_delay_ms=2.0, queue_bound=64,
        request_ttl_s=30.0,
        admission=AdmissionPolicy(rate_limit=rate,
                                  rate_burst=rate / 2)).start()
    schedule = FaultSchedule(4242, drop=0.04, corrupt=0.04,
                             duplicate=0.04, delay=0.04,
                             delay_s=(0.01, 0.04),
                             stall=0.25, stall_s=(0.005, 0.02))
    proxy = ChaosProxy("tcp://127.0.0.1:17594", srv.endpoint,
                       schedule).start()
    rng = np.random.default_rng(17)
    x1 = rng.normal(0, 1, (1, 784)).astype(np.float32)
    payloads = [rng.normal(0, 1, (1 + i % 4, 784)).astype(np.float32)
                for i in range(18)]
    ladder = srv.batcher.ladder
    # generation-1 oracles for every payload at every rung it may ride,
    # computed BEFORE the swap exists (gen-1 params are gone after)
    ref_a_full = {
        i: [srv.runner.infer(srv.runner.pad(x, b))[:len(x)]
            for b in ladder.rungs if b >= len(x)]
        for i, x in enumerate(payloads)}
    ref_a_full["flood"] = [srv.runner.infer(srv.runner.pad(x1, b))[:1]
                           for b in ladder.rungs]
    path_b = _perturbed_snapshot(wf, tmp_path)
    srv.runner.inject_compute_faults(schedule)
    got = [None] * len(payloads)          # (gen, y) per request
    errs = []

    def worker(wid):
        cli = InferenceClient("tcp://127.0.0.1:17594", timeout=120,
                              resend_after_s=0.3)
        try:
            for i in range(wid, len(payloads), 3):
                rep = cli.result(cli.submit(payloads[i]))
                got[i] = (rep["gen"], rep["y"])
        except Exception as exc:          # pragma: no cover - failure
            errs.append((wid, exc))
        finally:
            cli.close()

    flood = FloodDriver("tcp://127.0.0.1:17594", x1, rate,
                        factor=10.0).start()
    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    srv.swap_async(path_b)                # rollover mid-chaos
    try:
        for t in threads:
            t.join(timeout=240)
        flood.stop()
        assert not errs, errs
        assert all(g is not None for g in got)
        t0 = time.perf_counter()
        while srv.runner.swapping:        # let the flip land
            assert time.perf_counter() - t0 < 60
            time.sleep(0.01)
        assert srv.runner.generation == 2
        srv.runner.inject_compute_faults(FaultSchedule(1, stall=0.0))
        ref_b_full = {
            i: [srv.runner.infer(srv.runner.pad(x, b))[:len(x)]
                for b in ladder.rungs if b >= len(x)]
            for i, x in enumerate(payloads)}
        assert not np.array_equal(ref_a_full[0][0], ref_b_full[0][0])
        # bit-exact under the STAMPED generation, at whatever rung the
        # request rode — zero cross-request/cross-generation leakage
        for i, (g, y) in enumerate(got):
            assert g in (1, 2), (i, g)
            refs = ref_a_full[i] if g == 1 else ref_b_full[i]
            assert any(np.array_equal(y, r) for r in refs), (i, g)
        assert flood.accepted > 0
        assert flood.refusals.get("rate_limited", 0) > 0
        assert set(flood.refusals) == {"rate_limited"}, flood.refusals
        assert srv.bad_frames == proxy.counters["req"]["corrupt"]
        assert srv.runner._m_stalls.value > 0
        assert srv.served >= len(payloads)
    finally:
        flood.stop()
        proxy.stop()
        srv.stop()


def test_web_status_serving_panel():
    from znicz_tpu.serving import InferenceClient, InferenceServer
    from znicz_tpu.web_status import WebStatus

    wf = _tiny_mnist_wf()
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=2.0).start()
    status = WebStatus(port=0).start()
    cli = InferenceClient(srv.endpoint, timeout=30)
    try:
        status.register(wf)
        status.register_inference(srv)
        cli.infer(np.zeros((2, 784), np.float32))
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/status.json") as r:
            snap = json.load(r)
        panel = snap["serving"]
        assert panel["served"] >= 1
        assert panel["endpoint"] == srv.endpoint
        for key in ("qps", "p50_ms", "p99_ms", "rejected", "timed_out",
                    "bad_frames"):
            assert key in panel
        assert panel["batcher"]["queue_depth"] == 0
        assert sum(panel["batcher"]["bucket_hits"].values()) >= 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/") as r:
            page = r.read().decode()
        assert "Serving" in page and "occupancy" in page
    finally:
        cli.close()
        status.stop()
        srv.stop()


def test_launcher_serve_cli():
    from znicz_tpu.launcher import main
    from znicz_tpu.serving import InferenceClient

    # role flags are mutually exclusive
    assert main(["mnist", "--serve", "--master"]) == 2

    endpoint = "tcp://127.0.0.1:17592"
    root.common.serving.max_requests = 2
    rc = {}

    def run_cli():
        rc["code"] = main([
            "mnist", "--serve", endpoint,
            "root.mnist.loader.n_train=120",
            "root.mnist.loader.n_valid=60",
            "root.mnist.loader.minibatch_size=60",
        ])

    t = threading.Thread(target=run_cli)
    t.start()
    try:
        cli = InferenceClient(endpoint, timeout=90, resend_after_s=2.0)
        try:
            y = cli.infer(np.zeros((2, 784), np.float32), timeout=90)
            assert y.shape == (2, 10)
            cli.infer(np.zeros((1, 784), np.float32), timeout=90)
        finally:
            cli.close()
        t.join(timeout=60)
        assert not t.is_alive()
        assert rc["code"] == 0
    finally:
        root.common.serving.max_requests = None
        t.join(timeout=5)
