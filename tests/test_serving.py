"""Dynamic-batching inference serving layer (ISSUE 4): batcher policy
units, 0-ULP batched-vs-unbatched parity, bucket-ladder jit-cache
hygiene, the wire Codec extraction, snapshot inference-load, the
ChaosProxy soak, the web panel, and the --serve CLI."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.config import root


def _tiny_mnist_wf(n_train=120, layers=None):
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = n_train
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    if layers is not None:
        root.mnist.layers = list(layers)
    try:
        wf = mnist.MnistWorkflow()
    finally:
        root.mnist.layers = [100, 10]
    wf.initialize(device=None)
    return wf


# -- batcher policy -----------------------------------------------------------


def test_bucket_ladder():
    from znicz_tpu.serving import BucketLadder

    lad = BucketLadder(32)
    assert lad.rungs == [1, 2, 4, 8, 16, 32]
    assert lad.bucket_for(1) == 1
    assert lad.bucket_for(3) == 4
    assert lad.bucket_for(32) == 32
    with pytest.raises(ValueError):
        lad.bucket_for(33)
    # non-power-of-two max_batch gets its own top rung
    assert BucketLadder(24).rungs == [1, 2, 4, 8, 16, 24]
    # explicit rungs must end at max_batch
    with pytest.raises(ValueError):
        BucketLadder(8, rungs=[1, 4])


def _req(n):
    from znicz_tpu.serving import Request

    return Request(np.zeros((n, 4), np.float32), n, req_id=n)


def test_batcher_coalesces_under_max_batch():
    from znicz_tpu.serving import DynamicBatcher

    b = DynamicBatcher(max_batch=8, max_delay_ms=50.0, queue_bound=100)
    for n in (3, 2, 2, 4):              # 3+2+2 fit; 4 would overflow
        assert b.submit(_req(n)) is None
    batch = b.next_batch(timeout=0.5)
    assert [r.n for r in batch] == [3, 2, 2]   # order preserved, 4 left
    assert b.queue_depth == 4
    batch2 = b.next_batch(timeout=0.5)
    assert [r.n for r in batch2] == [4]
    assert b.bucket_hits[8] == 1 and b.bucket_hits[4] == 1
    assert b.batched_rows == 11 and b.padded_rows == (8 - 7) + 0


def test_batcher_max_delay_flushes_partial():
    from znicz_tpu.serving import DynamicBatcher

    b = DynamicBatcher(max_batch=32, max_delay_ms=30.0, queue_bound=100)
    b.submit(_req(2))
    t0 = time.perf_counter()
    batch = b.next_batch(timeout=1.0)
    waited = time.perf_counter() - t0
    assert [r.n for r in batch] == [2]
    assert 0.02 <= waited < 0.5          # the window, not the timeout
    # wait_fill=False takes only what is queued, instantly
    b.submit(_req(1))
    t0 = time.perf_counter()
    batch = b.next_batch(timeout=1.0, wait_fill=False)
    assert [r.n for r in batch] == [1]
    assert time.perf_counter() - t0 < 0.02


def test_batcher_backpressure_sheds_at_bound():
    from znicz_tpu.serving import DynamicBatcher

    b = DynamicBatcher(max_batch=4, max_delay_ms=1.0, queue_bound=10)
    for _ in range(5):
        assert b.submit(_req(2)) is None
    reason = b.submit(_req(2))           # 12 rows would exceed 10
    assert reason is not None and "shed" in reason
    assert b.shed == 1
    # oversized is refused outright, never queued
    reason = b.submit(_req(5))
    assert reason is not None and "max_batch" in reason
    assert b.oversized == 1
    assert b.queue_depth == 10


# -- codec extraction (ISSUE 4 satellite) -------------------------------------


def test_codec_frames_byte_identical_and_counted():
    from znicz_tpu.parallel import wire

    msg = {"cmd": "infer", "req_id": 7,
           "x": np.arange(12, dtype=np.float32).reshape(3, 4)}
    bare, info = wire.encode_message(msg)
    codec = wire.Codec()
    framed = codec.encode(msg)
    assert [bytes(f) for f in framed] == [bytes(f) for f in bare]
    assert codec.bytes_out == sum(len(bytes(f)) for f in bare)
    assert codec.tensor_bytes_wire_out == info["wire_bytes"]
    dec, dinfo = codec.decode([bytes(f) for f in framed])
    assert np.array_equal(dec["x"], msg["x"])
    assert codec.bytes_in == codec.bytes_out
    assert dinfo["message_bytes"] == codec.bytes_in
    assert codec.compression_ratio("in") == pytest.approx(1.0)
    # refusal: counted, legacy-framed (single pickle any peer can read)
    frames = codec.refusal("bad frame: torn")
    assert codec.bad_frames == 1
    import pickle

    rep = pickle.loads(frames[0])
    assert rep["bad_frame"] and "torn" in rep["error"]


def test_server_counters_ride_the_codec(tmp_path):
    """The Server's historical counter names read/write its Codec (the
    resume snapshot setattr's them by name)."""
    from znicz_tpu.server import Server

    wf = _tiny_mnist_wf()
    srv = Server(wf, endpoint="tcp://127.0.0.1:17579")
    srv.bytes_in = 123
    assert srv.codec.bytes_in == 123
    srv.bad_frames += 1
    assert srv.codec.bad_frames == 1
    srv.codec.tensor_bytes_raw_in = 40
    srv.codec.tensor_bytes_wire_in = 10
    assert srv.compression_ratio("in") == pytest.approx(4.0)


# -- model runner: parity + jit-cache hygiene ---------------------------------


def test_batched_vs_unbatched_parity_0ulp_and_padding_masked():
    """The dynamic-batching correctness contract, to 0 ULP: a request's
    result rows are a pure function of ITS rows and the bucket
    executable — independent of what it was coalesced with, its offset
    inside the batch, and the pad content.  (Parity is per BUCKET: XLA
    compiles a different executable per batch shape, and e.g. the
    batch-1 gemv path legitimately differs from the gemm path in final
    bits — which is exactly why the ladder pins the executable set.)"""
    from znicz_tpu.serving import ModelRunner

    wf = _tiny_mnist_wf()
    runner = ModelRunner(wf)
    rng = np.random.default_rng(7)
    xs = [rng.normal(0, 1, (n, 784)).astype(np.float32)
          for n in (1, 4, 3)]            # 8 rows: one bucket-8 batch
    # unbatched reference: each request served ALONE in bucket 8
    alone = [runner.infer(runner.pad(x, 8))[:len(x)] for x in xs]
    # coalesced: all three share one bucket-8 batch
    batched = runner.infer(np.concatenate(xs, axis=0))
    off = 0
    for x, ref in zip(xs, alone):
        assert np.array_equal(batched[off:off + len(x)], ref)
        off += len(x)
    # padding is masked out of results AND cannot leak in: garbage pad
    # rows leave the real rows bit-identical
    garbage = runner.pad(xs[2], 8)
    garbage[3:] = 1e9
    assert np.array_equal(runner.infer(garbage)[:3], alone[2])


def test_warmup_compiles_ladder_then_zero_recompiles():
    from znicz_tpu.serving import BucketLadder, ModelRunner

    wf = _tiny_mnist_wf()
    runner = ModelRunner(wf)
    ladder = BucketLadder(8)
    n = runner.warmup(ladder)
    assert n == len(ladder.rungs)
    if runner.jit_cache_size() is not None:
        assert runner.jit_cache_size() == n
    for rows in (1, 3, 7, 8, 2, 5, 4, 6):
        runner.infer(np.zeros((ladder.bucket_for(rows),) + (784,),
                              np.float32))
    assert runner.compiles == n          # every bucket was a cache hit


# -- snapshot inference-load path ---------------------------------------------


def test_snapshot_inference_load(tmp_path):
    from znicz_tpu import snapshotter
    from znicz_tpu.serving import ModelRunner

    wf = _tiny_mnist_wf()
    wf.snapshotter.directory = str(tmp_path)   # before run(): the
    # on-improvement save must not land in the repo's snapshots/
    root.mnist.decision.max_epochs = 1
    try:
        wf.run()
    finally:
        root.mnist.decision.max_epochs = 5
    path = wf.snapshotter.save("serve_test")
    trained = {f.name: {k: np.array(a.map_read())
                        for k, a in f.params().items()}
               for f in wf.forwards}

    fresh = _tiny_mnist_wf()
    meta = snapshotter.load_inference(fresh, path)
    assert "units" not in meta and "epoch" in meta
    for f in fresh.forwards:
        for k, a in f.params().items():
            np.testing.assert_array_equal(np.array(a.map_read()),
                                          trained[f.name][k])
    # the served forward IS the trained function
    runner = ModelRunner(fresh)
    x = np.asarray(wf.loader.original_data.mem[:5], np.float32)
    y = runner.infer(x)
    assert y.shape == (5, 10) and np.all(np.isfinite(y))

    # a snapshot that does not cover the model's weighted layers is
    # refused, not silently half-served
    with pytest.raises(ValueError, match="no params"):
        snapshotter.restore_inference(fresh, {"units": {"fwd0": {}}})


# -- end-to-end service -------------------------------------------------------


def test_e2e_mixed_sizes_parity_and_stats():
    from znicz_tpu.serving import (InferenceClient, InferenceError,
                                   InferenceServer)

    wf = _tiny_mnist_wf()
    srv = InferenceServer(wf, max_batch=8, max_delay_ms=3.0,
                          queue_bound=64).start()
    cli = InferenceClient(srv.endpoint, timeout=30)
    try:
        compiles_warm = srv.runner.compiles
        ladder = srv.batcher.ladder
        rng = np.random.default_rng(11)
        for n in (1, 3, 8, 2, 5, 1, 7, 4):
            x = rng.normal(0, 1, (n, 784)).astype(np.float32)
            y = cli.infer(x)
            # 0 ULP e2e vs the request served directly at its bucket
            ref = srv.runner.infer(
                srv.runner.pad(x, ladder.bucket_for(n)))[:n]
            assert np.array_equal(y, ref)
        # a bare sample (no batch axis) is accepted
        y = cli.infer(rng.normal(0, 1, (784,)).astype(np.float32))
        assert y.shape == (1, 10)
        assert srv.runner.compiles == compiles_warm   # zero recompiles
        # oversized requests are refused with the reason, not dropped
        with pytest.raises(InferenceError, match="max_batch"):
            cli.infer(np.zeros((9, 784), np.float32))
        # wrong sample shape is refused readably
        with pytest.raises(InferenceError, match="sample shape"):
            cli.infer(np.zeros((2, 77), np.float32))
        # control commands + the stats payload the web panel shows
        assert cli.ping()["pong"]
        stats = cli.stats()
        assert stats["served"] >= 9 and stats["rejected"] >= 1
        assert stats["p50_ms"] is not None
        assert sum(stats["batcher"]["bucket_hits"].values()) \
            == stats["batcher"]["batches"]
        assert stats["model"]["compiles"] == compiles_warm
    finally:
        cli.close()
        srv.stop()


def test_start_surfaces_real_bind_error():
    """start() re-raises the serve thread's actual failure (bind
    conflict here) instead of hanging out a timeout and masking it."""
    from znicz_tpu.serving import InferenceServer

    wf = _tiny_mnist_wf()
    srv = InferenceServer(wf, max_batch=2, max_delay_ms=1.0).start()
    try:
        with pytest.raises(RuntimeError, match="failed on"):
            InferenceServer(wf, bind=srv.endpoint, max_batch=2,
                            max_delay_ms=1.0).start()
    finally:
        srv.stop()


def test_e2e_undecodable_frames_refused_not_fatal():
    """A garbage request is refused with a counted error reply and the
    service keeps serving — the master's bad-frame fault model extends
    to serving."""
    import zmq

    from znicz_tpu.serving import InferenceClient, InferenceServer

    wf = _tiny_mnist_wf()
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=2.0).start()
    ctx = zmq.Context.instance()
    raw = ctx.socket(zmq.DEALER)
    raw.setsockopt(zmq.RCVTIMEO, 10_000)
    raw.setsockopt(zmq.LINGER, 0)
    raw.connect(srv.endpoint)
    cli = InferenceClient(srv.endpoint, timeout=30)
    try:
        from znicz_tpu.parallel import wire

        raw.send_multipart([b"\xff garbage \x00"])
        rep, _ = wire.decode_message(raw.recv_multipart())
        assert rep["bad_frame"] is True
        assert srv.bad_frames == 1
        # the service still answers real requests afterwards
        y = cli.infer(np.zeros((2, 784), np.float32))
        assert y.shape == (2, 10)
    finally:
        raw.close(0)
        cli.close()
        srv.stop()


def test_chaos_soak_serving():
    """Multi-client soak through the seeded ChaosProxy: dropped and
    corrupted frames in BOTH directions, duplicated and delayed
    messages — every request still completes with bit-exact results
    (resend + req_id dedup), the server never crashes, and every
    corrupted request-direction message is accounted in ``bad_frames``
    exactly like the master's fault model."""
    from znicz_tpu.parallel.chaos import ChaosProxy, FaultSchedule
    from znicz_tpu.serving import InferenceClient, InferenceServer

    wf = _tiny_mnist_wf()
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=2.0,
                          queue_bound=64).start()
    proxy = ChaosProxy("tcp://127.0.0.1:17591", srv.endpoint,
                       FaultSchedule(2024, drop=0.05, corrupt=0.06,
                                     duplicate=0.04, delay=0.05,
                                     delay_s=(0.01, 0.05))).start()
    errs = []
    rng = np.random.default_rng(5)
    payloads = [rng.normal(0, 1, (1 + i % 4, 784)).astype(np.float32)
                for i in range(12)]
    expected = [None] * len(payloads)

    def worker(wid):
        cli = InferenceClient("tcp://127.0.0.1:17591", timeout=60,
                              resend_after_s=0.3)
        try:
            for i in range(wid, len(payloads), 3):
                y = cli.infer(payloads[i])
                expected[i] = y
        except Exception as exc:        # pragma: no cover - failure path
            errs.append((wid, exc))
        finally:
            cli.close()

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    try:
        assert not errs, errs
        assert all(e is not None for e in expected)
        # bit-exact through the chaos: concurrent clients coalesce, so
        # a request may have been served under ANY rung >= its rows —
        # its bits must match that rung's executable exactly (pure
        # function of own rows + bucket; zero cross-request leakage)
        ladder = srv.batcher.ladder
        for i, x in enumerate(payloads):
            refs = [srv.runner.infer(srv.runner.pad(x, b))[:len(x)]
                    for b in ladder.rungs if b >= len(x)]
            assert any(np.array_equal(expected[i], ref)
                       for ref in refs), i
        # accounting: every corrupted request-direction message the
        # proxy injected was refused and counted by the server
        assert srv.bad_frames == proxy.counters["req"]["corrupt"]
        if proxy.counters["req"]["corrupt"]:
            assert srv.bad_frames > 0
        assert srv.served >= len(payloads)
    finally:
        proxy.stop()
        srv.stop()


def test_web_status_serving_panel():
    from znicz_tpu.serving import InferenceClient, InferenceServer
    from znicz_tpu.web_status import WebStatus

    wf = _tiny_mnist_wf()
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=2.0).start()
    status = WebStatus(port=0).start()
    cli = InferenceClient(srv.endpoint, timeout=30)
    try:
        status.register(wf)
        status.register_inference(srv)
        cli.infer(np.zeros((2, 784), np.float32))
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/status.json") as r:
            snap = json.load(r)
        panel = snap["serving"]
        assert panel["served"] >= 1
        assert panel["endpoint"] == srv.endpoint
        for key in ("qps", "p50_ms", "p99_ms", "rejected", "timed_out",
                    "bad_frames"):
            assert key in panel
        assert panel["batcher"]["queue_depth"] == 0
        assert sum(panel["batcher"]["bucket_hits"].values()) >= 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/") as r:
            page = r.read().decode()
        assert "Serving" in page and "occupancy" in page
    finally:
        cli.close()
        status.stop()
        srv.stop()


def test_launcher_serve_cli():
    from znicz_tpu.launcher import main
    from znicz_tpu.serving import InferenceClient

    # role flags are mutually exclusive
    assert main(["mnist", "--serve", "--master"]) == 2

    endpoint = "tcp://127.0.0.1:17592"
    root.common.serving.max_requests = 2
    rc = {}

    def run_cli():
        rc["code"] = main([
            "mnist", "--serve", endpoint,
            "root.mnist.loader.n_train=120",
            "root.mnist.loader.n_valid=60",
            "root.mnist.loader.minibatch_size=60",
        ])

    t = threading.Thread(target=run_cli)
    t.start()
    try:
        cli = InferenceClient(endpoint, timeout=90, resend_after_s=2.0)
        try:
            y = cli.infer(np.zeros((2, 784), np.float32), timeout=90)
            assert y.shape == (2, 10)
            cli.infer(np.zeros((1, 784), np.float32), timeout=90)
        finally:
            cli.close()
        t.join(timeout=60)
        assert not t.is_alive()
        assert rc["code"] == 0
    finally:
        root.common.serving.max_requests = None
        t.join(timeout=5)
