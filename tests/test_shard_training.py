"""Pod-sliced training (ISSUE 18): mesh-sharded FusedTrainer steps.

Covers: the ``root.common.engine.train_shard`` gate and its mesh
refusals, the extraction proof (serving imports ONLY the shared
placement home, the param-sharding rule lives in exactly one file),
per-device shard shapes on 4x1 and 2x2 slices, 1x1-resolves-to-
single-device bit-exactness, the cross-layout convergence band
(reduction tiling is layout-dependent — same reason the serving
twin's cross-mesh parity is a band), the compiles==jit-cache
zero-recompile cross-check, sharded staged segments (``P(None,
"data")``, one transfer per shard) with DeviceStager telemetry, and
the meshed-slave-through-master e2e (register piggyback + web_status
mesh column).  The relay-leaf soak rides behind ``slow``.

Runs on the 8 virtual CPU devices conftest provisions (virtdev.py)."""

import pathlib
import threading

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.config import root

PKG = pathlib.Path(__file__).resolve().parents[1] / "znicz_tpu"


def _tiny_mnist_wf(n_train=120, layers=(1024, 10), max_epochs=2):
    """The shard-serving twin's workflow: hidden 1024 >= tp_threshold
    so the model axis engages the column-sharded layout."""
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = n_train
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = max_epochs
    root.mnist.layers = list(layers)
    try:
        wf = mnist.MnistWorkflow()
    finally:
        root.mnist.layers = [100, 10]
    wf.initialize(device=None)
    return wf


def _mesh(dp, mp=1):
    from znicz_tpu.parallel.mesh import make_mesh

    return make_mesh((dp, mp), ("data", "model"))


def _run_fused(wf, mesh=None):
    from znicz_tpu.parallel.fused import FusedTrainer

    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    t = FusedTrainer(wf, mesh=mesh)
    t.run()
    return t, losses, {f.name: np.array(f.weights.map_read())
                       for f in wf.forwards if f.has_weights}


@pytest.fixture
def engine_mesh(tmp_path):
    """Set the pod-slice knobs for a test and restore the defaults
    after — the global engine tree must not leak a mesh into the rest
    of the suite."""
    root.common.dirs.snapshots = str(tmp_path)

    def set_mesh(dp, mp=1, shard=True):
        root.common.engine.train_shard = bool(shard)
        root.common.engine.mesh.data = int(dp)
        root.common.engine.mesh.model = int(mp)
    yield set_mesh
    root.common.engine.train_shard = False
    try:
        delattr(root.common.engine, "mesh")
    except AttributeError:
        pass


# -- the config gate ----------------------------------------------------------


def test_train_mesh_config_gate_and_refusals(engine_mesh):
    from znicz_tpu.parallel.mesh import train_mesh_from_config

    # default OFF: single-device, whatever the mesh knobs say
    assert train_mesh_from_config() is None
    engine_mesh(4, 2, shard=False)
    assert train_mesh_from_config() is None
    # ON with 1x1 IS the single-device path
    engine_mesh(1, 1)
    assert train_mesh_from_config() is None
    # ON with a real slice
    engine_mesh(4, 1)
    m = train_mesh_from_config()
    assert m.axis_names == ("data", "model")
    assert (int(m.shape["data"]), int(m.shape["model"])) == (4, 1)
    # refusals are readable and name the plane
    engine_mesh(0, 2)
    with pytest.raises(ValueError, match="training mesh axes"):
        train_mesh_from_config()


# -- extraction proof (ISSUE 18 satellite 1) ----------------------------------


def test_serving_imports_only_the_shared_placement_home():
    """PR 12's placement machinery moved to parallel/mesh.py; the
    serving plane must now hold NO placement code of its own — only
    imports of the shared home."""
    src = (PKG / "serving" / "model.py").read_text()
    assert "from znicz_tpu.parallel.mesh import" in src
    for literal in ("make_array_from_callback", "NamedSharding(",
                    "PartitionSpec"):
        assert literal not in src, (
            f"serving/model.py still carries placement machinery "
            f"({literal}) — it must import parallel/mesh.py instead")


def test_param_sharding_rule_has_exactly_one_home():
    """The tp-threshold rule body (``shape[0] >= tp_threshold`` and
    the divisibility check) must exist in parallel/mesh.py and NOWHERE
    else — callers delegate, they do not duplicate."""
    owners = [p.relative_to(PKG).as_posix() for p in PKG.rglob("*.py")
              if ">= tp_threshold" in p.read_text()]
    assert owners == ["parallel/mesh.py"], owners


# -- shard shapes, bit-exactness, convergence band ----------------------------


def test_meshed_trainer_layouts_shapes_band_and_jit_hygiene(tmp_path):
    """One seeded run per layout (single-device, 4x1, 2x2): shard
    shapes per the param-sharding rule, losses/weights inside the
    cross-layout band, and compiles == jax's own executable-cache sum
    (the zero-recompile cross-check) on every layout."""
    root.common.dirs.snapshots = str(tmp_path)
    t1, l1, w1 = _run_fused(_tiny_mnist_wf())
    runs = {}
    for tag, (dp, mp) in (("d4", (4, 1)), ("d2m2", (2, 2))):
        t, ls, ws = _run_fused(_tiny_mnist_wf(), mesh=_mesh(dp, mp))
        runs[tag] = (t, ls, ws)
        assert t.mesh_shape == {"data": dp, "model": mp}
        # the wide fc layer: column-sharded over model (hidden/mp rows
        # per shard) when mp > 1, replicated otherwise; always one
        # shard per mesh device, never a device-0 gather
        wide = next(f for f in t.forwards
                    if f.has_weights and f.weights.shape[0] == 1024)
        shards = [s.data.shape
                  for s in wide.weights.devmem.addressable_shards]
        assert len(shards) == dp * mp
        assert all(s == (1024 // mp, 784) for s in shards), shards
        bshards = [s.data.shape
                   for s in wide.bias.devmem.addressable_shards]
        assert all(s == (1024 // mp,) for s in bshards), bshards
        # jit hygiene: the trace counter equals jax's cache entries
        sizes = t.jit_cache_sizes()
        if sizes:
            assert sum(sizes.values()) == int(t._m_compiles.value), sizes
        # cross-layout band (NOT 0 ULP: reduction tiling is layout-
        # dependent, exactly the serving twin's PARITY_REL rationale)
        np.testing.assert_allclose(l1, ls, rtol=1e-3)
        for name in w1:
            np.testing.assert_allclose(w1[name], ws[name], rtol=2e-3,
                                       atol=2e-5, err_msg=f"{tag}:{name}")
    assert l1[-1] < l1[0]                       # and it actually trains


def test_train_shard_mesh_1x1_is_bitexact_single_device(engine_mesh):
    """train_shard ON with a 1x1 mesh resolves to mesh=None — the
    IDENTICAL single-device path, bit for bit."""
    from znicz_tpu.parallel.mesh import train_mesh_from_config

    _, l_off, w_off = _run_fused(_tiny_mnist_wf(layers=(100, 10)))
    engine_mesh(1, 1)
    m = train_mesh_from_config()
    assert m is None
    _, l_on, w_on = _run_fused(_tiny_mnist_wf(layers=(100, 10)), mesh=m)
    assert l_off == l_on
    for name in w_off:
        assert np.array_equal(w_off[name], w_on[name]), name


# -- sharded staged segments (ISSUE 18 satellite 2) ---------------------------


def test_staged_segments_shard_over_data_with_telemetry(tmp_path):
    """Host-staged streaming on a (data, model) mesh: each staged
    (K, B, ...) segment is placed ``P(None, "data")`` — one transfer
    per shard, no device-0 gather — and the DeviceStager's ping-pong
    telemetry (stage hits/misses, h2d_copy_ms) covers the sharded
    path."""
    import jax
    from jax.sharding import PartitionSpec as P

    from znicz_tpu import datasets
    from znicz_tpu.loader.streaming import (HostArraySource,
                                            StreamingLoader)
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    root.common.dirs.snapshots = str(tmp_path)
    prng.reset(1013)
    root.mnist.loader.n_train = 240
    root.mnist.loader.n_valid = 60
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 2

    cfg = root.mnist.loader
    total = int(cfg.n_train) + int(cfg.n_valid)
    data, labels = datasets.load_or_generate(None, datasets.digits, total)

    class _Streaming(StreamingLoader):
        def __init__(self, workflow=None, name=None, **kwargs):
            super().__init__(
                workflow=workflow, name=name,
                source=HostArraySource(data.reshape(total, -1), labels),
                class_lengths=[0, int(cfg.n_valid), int(cfg.n_train)],
                scale=1.0, shift=0.0, device_budget_bytes=0, **kwargs)

    orig = mnist.MnistLoader
    mnist.MnistLoader = _Streaming
    try:
        wf = mnist.MnistWorkflow()
    finally:
        mnist.MnistLoader = orig
    wf.initialize(device=None)
    t = FusedTrainer(wf, mesh=_mesh(2, 2))
    assert t.staging
    # the staged segment itself: batch axis sharded over "data" (60 %
    # dp == 0), replicated over "model" — (K, B/dp, ...) per shard
    seg_d, seg_t = t._stage_direct(
        [np.arange(60, dtype=np.int32),
         np.arange(60, 120, dtype=np.int32)], put=None)
    assert seg_d.sharding.spec == P(None, "data")
    shapes = [s.data.shape for s in seg_d.addressable_shards]
    assert len(shapes) == 4 and all(s == (2, 30, 784) for s in shapes)
    assert seg_t.sharding.spec == P(None, "data")
    del seg_d, seg_t
    t.run()
    assert wf.decision.epoch_metrics[2]["loss"] < 2.0   # it trains
    st = t._stager.stats()
    assert st["stage_hits"] + st["stage_misses"] > 0
    assert st["h2d_ms_p50"] is not None     # the copies were timed
    jax.clear_caches()


# -- ring attention on the training mesh --------------------------------------


def test_bind_sequence_mesh_refusals_and_parity():
    """``bind_sequence_mesh`` rebinds MHA's shard_map onto a training
    mesh (batch over "data", ring blocks over "model"); a mesh whose
    seq axis cannot ring (size < 2) is refused; the bound path matches
    the dense core numerically."""
    from znicz_tpu.attention import MultiHeadAttention
    from znicz_tpu.memory import Array

    rng = np.random.default_rng(47)
    x = rng.normal(size=(2, 32, 8)).astype(np.float32)

    def build(name):
        mha = MultiHeadAttention(name=name, heads=2, causal=True)
        mha.input = Array(x)
        mha.initialize(device=None)
        return mha

    base = build("mha_tm_off")
    base.run()
    ref = np.array(base.output.map_read())
    bound = build("mha_tm_on")
    assert bound.bind_sequence_mesh(None) is False
    assert bound.bind_sequence_mesh(_mesh(4, 1)) is False   # no ring
    assert bound.bind_sequence_mesh(_mesh(2, 2)) is True
    assert bound._sp_spec == ("data", "model")
    for kk, a in base.proj.items():                # identical weights
        bound.proj[kk].mem = np.array(a.map_read())
    bound.run()
    np.testing.assert_allclose(np.array(bound.output.map_read()), ref,
                               rtol=2e-4, atol=1e-5)


def test_meshed_trainer_rebinds_charlm_attention(tmp_path):
    """seq_parallel on a meshed FusedTrainer rides the TRAINING mesh
    instead of the private ("sp",) mesh initialize() builds — one mesh
    per leaf, not two fighting over the same devices."""
    from znicz_tpu.attention import MultiHeadAttention
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples.charlm import CharLMWorkflow

    root.common.dirs.snapshots = str(tmp_path)
    prng.reset(1013)
    root.charlm.loader.update({"n_train": 64, "n_valid": 32,
                               "n_test": 0, "seq_len": 32,
                               "minibatch_size": 32})
    root.charlm.model.update({"vocab": 32, "embed": 48, "heads": 2,
                              "ffn": 96})
    root.charlm.decision.max_epochs = 1
    try:
        root.common.engine.seq_parallel = 2
        wf = CharLMWorkflow()
        wf.initialize(device=None)
        mesh = _mesh(2, 2)
        t = FusedTrainer(wf, mesh=mesh)
        mha = next(f for f in t.forwards
                   if isinstance(f, MultiHeadAttention))
        assert mha._sp_mesh is mesh
        assert mha._sp_spec == ("data", "model")
    finally:
        root.common.engine.seq_parallel = 0


# -- meshed slave through the master (ISSUE 18 e2e) ---------------------------


def _fleet(endpoint, engine_mesh=None, dp=2, mp=2):
    """One seeded master + one FusedClient slave over `endpoint`;
    returns (server, master_wf, slave)."""
    from znicz_tpu.client import FusedClient
    from znicz_tpu.server import Server

    wf = _tiny_mnist_wf()
    server = Server(wf, endpoint=endpoint, job_timeout=60.0)
    slave = FusedClient(_tiny_mnist_wf(), endpoint=endpoint,
                        slave_id="pod0")
    errors = []

    def worker():
        try:
            slave.run()
        except BaseException as e:      # surface thread crashes
            errors.append(repr(e))
            raise

    th = threading.Thread(target=worker, daemon=True)
    th.start()
    server.serve()
    th.join(timeout=60)
    assert not errors, errors
    assert not th.is_alive()
    assert bool(wf.decision.complete)
    return server, wf, slave


def test_meshed_slave_e2e_piggyback_and_web_status(engine_mesh):
    """A pod-sliced FusedClient trains a real (tiny) fleet to
    completion: the slice shape rides the register handshake onto the
    master and into the web_status mesh column; the slave's params
    end up column-sharded; the wire saw a normal single slave."""
    from znicz_tpu.network_common import handshake_request
    from znicz_tpu.web_status import WebStatus

    engine_mesh(2, 2)
    server, wf, slave = _fleet("tcp://127.0.0.1:18930")
    assert slave.mesh_shape == {"data": 2, "model": 2}
    assert server.slave_meshes == {"pod0": {"data": 2, "model": 2}}
    assert int(server.bytes_in) > 0
    # web_status: the mesh column renders the slice (single-device
    # slaves show None -> "single-device")
    ws = WebStatus()
    ws.register_server(server)
    rows = ws.snapshot()["master"]["slaves"]
    assert [r["mesh"] for r in rows if r["id"] == "pod0"] == [
        {"data": 2, "model": 2}]
    # the piggyback is OPTIONAL on the wire: no mesh -> no key (an
    # older master ignores it either way)
    assert "mesh" not in handshake_request(wf)
    assert handshake_request(wf, mesh={"data": 2, "model": 2})[
        "mesh"] == {"data": 2, "model": 2}
    # the slave's wide layer really is sharded on its slice
    t = slave._trainer
    wide = next(f for f in t.forwards
                if f.has_weights and f.weights.shape[0] == 1024)
    shards = [s.data.shape
              for s in wide.weights.devmem.addressable_shards]
    assert len(shards) == 4 and all(s == (512, 784) for s in shards)
    # zero-recompile cross-check on the slave's executables
    sizes = t.jit_cache_sizes()
    if sizes:
        assert sum(sizes.values()) == int(t._m_compiles.value), sizes


@pytest.mark.slow
def test_meshed_slave_through_relay_soak(engine_mesh):
    """The pod slice composes with the tree (ISSUE 10): a meshed leaf
    behind a relay trains to completion, and the relay's contributor
    manifest still attributes its jobs."""
    from znicz_tpu.client import FusedClient
    from znicz_tpu.parallel.chaos import RelayHarness
    from znicz_tpu.server import Server

    engine_mesh(2, 2)
    master_ep = "tcp://127.0.0.1:18940"
    relay_ep = "tcp://127.0.0.1:18941"
    wf = _tiny_mnist_wf()
    server = Server(wf, endpoint=master_ep, job_timeout=60.0)
    server_thread = threading.Thread(target=server.serve, daemon=True)
    server_thread.start()
    harness = RelayHarness(master_ep, relay_ep, relay_id="r0",
                           recv_timeout=1.0, max_reconnects=60)
    harness.start()
    try:
        slave = FusedClient(_tiny_mnist_wf(), endpoint=relay_ep,
                            slave_id="pod0")
        slave.run(recv_timeout=1.0, max_reconnects=80,
                  backoff_base=0.05, backoff_cap=0.4,
                  connect_retries=80)
        server_thread.join(timeout=60)
        assert not server_thread.is_alive()
    finally:
        harness.kill()
    assert slave.mesh_shape == {"data": 2, "model": 2}
    assert bool(wf.decision.complete)
    # the leaf's jobs are still attributed through the relay manifest
    assert server.jobs_by_slave.get("pod0", 0) > 0
