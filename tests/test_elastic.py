"""Elastic async training on the relay tree (ISSUE 11): bounded
staleness (exactly-at-bound applies, past-it refuses-and-requeues with
no strike, star AND tree), staleness-weighted applies, the min_slaves
quorum gate + degraded readiness, elastic counters through a resume
round trip, the runtime re-planner + orphan-leaf rehoming, relay
upstream re-homing (tree healing), the seeded subtree-preemption
schedule/driver, and (slow) a full preemption soak."""

import threading
import time

import numpy as np
import pytest

from znicz_tpu.core.config import root


def _make_workflow(tmp_path, max_epochs=3, n_train=300):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = n_train
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = max_epochs
    root.common.dirs.snapshots = str(tmp_path)
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def _handshake_fields(workflow):
    from znicz_tpu.network_common import handshake_request

    msg = handshake_request(workflow)
    del msg["cmd"]
    return msg


def _shapes(wf):
    return {f.name: {k: tuple(a.shape) for k, a in f.params().items()}
            for f in wf.forwards if f.has_weights}


def _delta(shapes, value=1e-4):
    return {n: {k: np.full(s, value, np.float32)
                for k, s in layer.items()}
            for n, layer in shapes.items()}


def _params(wf):
    return {f.name: {k: np.array(a.map_read())
                     for k, a in f.params().items()}
            for f in wf.forwards if f.has_weights}


def _assert_params(wf, want):
    for f in wf.forwards:
        if f.has_weights:
            for k, a in f.params().items():
                np.testing.assert_allclose(np.array(a.map_read()),
                                           want[f.name][k], rtol=1e-5)


# -- bounded staleness: the star ------------------------------------------------


def test_staleness_boundary_star_and_weighting(tmp_path):
    """Job replies are stamped with the apply counter and the slave
    echoes the stamp; a delta EXACTLY at the bound applies, one past it
    is refused-and-requeued (``stale_refused``, no bad-reply strike)
    and the job re-dispatches once; with weighting on, a staleness-1
    delta lands at half magnitude."""
    from znicz_tpu.server import Server

    wf = _make_workflow(tmp_path / "m")
    shapes = _shapes(wf)
    server = Server(wf, staleness_bound=1)
    assert server._handle({"cmd": "register", "id": "s1",
                           **_handshake_fields(wf)})["ok"]
    reps = [server._handle({"cmd": "job", "id": "s1"}) for _ in range(3)]
    assert all(r["step"] == 0 for r in reps)

    def update(rep, **extra):
        return server._handle({"cmd": "update", "id": "s1",
                               "job_id": rep["job_id"],
                               "step": rep["step"],
                               "deltas": _delta(shapes),
                               "metrics": {"loss": 1.0, "n_err": 0},
                               **extra})

    assert update(reps[0])["ok"] is True        # fresh: s = 0
    assert server.apply_step == 1
    assert update(reps[1])["ok"] is True        # s = 1 == bound: applies
    assert server.apply_step == 2
    assert server.stale_refused == 0
    before = _params(wf)
    rep = update(reps[2])                       # s = 2 > bound
    assert rep["ok"] is False and rep.get("stale_refused")
    assert rep["staleness"] == 2
    assert server.stale_refused == 1
    assert server.apply_step == 2               # nothing landed
    _assert_params(wf, before)
    assert len(server._pending) == 1            # re-queued...
    assert "_bad_replies" not in server._pending[0]     # ...no strike
    redis = server._handle({"cmd": "job", "id": "s1"})  # re-dispatched
    assert redis["job"] == reps[2]["job"]
    assert redis["step"] == 2
    assert update(redis)["ok"] is True          # fresh again: lands
    ledger = server.jobs_ledger()
    assert ledger["balanced"], ledger
    assert ledger == {"dispatched": 4, "jobs_done": 3,
                      "jobs_requeued": 0, "bad_updates": 0,
                      "quarantined_updates": 0, "stale_refused": 1,
                      "in_flight": 0, "balanced": True}
    assert server.staleness_summary()["s1"]["max"] == 2

    # a peer whose stamp echo is deterministically broken (always far
    # beyond the bound) must not livelock the refuse/refetch cycle:
    # after MAX_BAD_REPLIES stale refusals the non-tail job is DROPPED
    for n in range(server.MAX_BAD_REPLIES):
        j = server._handle({"cmd": "job", "id": "s1"})
        rep = server._handle({"cmd": "update", "id": "s1",
                              "job_id": j["job_id"], "step": 0,
                              "deltas": _delta(shapes),
                              "metrics": {"loss": 1.0, "n_err": 0}})
        assert rep["ok"] is False and rep.get("stale_refused")
    assert server.stale_refused == 1 + server.MAX_BAD_REPLIES
    assert len(server._pending) == 0        # dropped, not re-queued
    assert server.jobs_ledger()["balanced"], server.jobs_ledger()

    # -- staleness-weighted apply (1/(1+s)) on a fresh Server -----------
    # (fresh workflow too: the livelock loop above walked the shared
    # loader to the epoch tail, where job fetches answer ``wait``)
    wf = _make_workflow(tmp_path / "m2")
    shapes = _shapes(wf)
    server2 = Server(wf, staleness_weight=True)
    assert server2._handle({"cmd": "register", "id": "s1",
                            **_handshake_fields(wf)})["ok"]
    j1 = server2._handle({"cmd": "job", "id": "s1"})
    j2 = server2._handle({"cmd": "job", "id": "s1"})
    d = _delta(shapes, 2e-4)
    assert server2._handle({"cmd": "update", "id": "s1",
                            "job_id": j1["job_id"], "step": j1["step"],
                            "deltas": d,
                            "metrics": {"loss": 1.0, "n_err": 0}})["ok"]
    assert server2.weighted_applies == 0        # fresh: full weight
    mid = _params(wf)
    assert server2._handle({"cmd": "update", "id": "s1",
                            "job_id": j2["job_id"], "step": j2["step"],
                            "deltas": d,
                            "metrics": {"loss": 1.0, "n_err": 0}})["ok"]
    assert server2.weighted_applies == 1        # s = 1 -> x 1/2
    want = {n: {k: mid[n][k] + d[n][k] / 2.0 for k in layer}
            for n, layer in d.items()}
    _assert_params(wf, want)
    # a GARBAGE stamp from a broken peer degrades to "fresh" — the job
    # (already popped) must not be lost to an exception
    j3 = server2._handle({"cmd": "job", "id": "s1"})
    rep = server2._handle({"cmd": "update", "id": "s1",
                           "job_id": j3["job_id"], "step": "garbage",
                           "deltas": d,
                           "metrics": {"loss": 1.0, "n_err": 0}})
    assert rep["ok"] is True
    assert server2.jobs_ledger()["balanced"]


# -- bounded staleness: the tree ------------------------------------------------


def test_staleness_boundary_tree_aborts_indivisible_aggregate(tmp_path):
    """Through a relay manifest: a contributor exactly at the bound
    applies; one past it is baked into the INDIVISIBLE sum, so the
    whole aggregate is refused — the over-bound child re-queues under
    ``stale_refused``, innocent siblings under ``jobs_requeued``,
    nobody takes a bad-reply strike, and the books stay balanced."""
    from znicz_tpu.server import Server

    wf = _make_workflow(tmp_path / "m")
    shapes = _shapes(wf)
    server = Server(wf, staleness_bound=1)
    assert server._handle({"cmd": "register", "id": "r", "relay": True,
                           "bind": "tcp://127.0.0.1:9",
                           **_handshake_fields(wf)})["ok"]
    rep = server._handle({"cmd": "job", "id": "r", "count": 5})
    jids = [e["job_id"] for e in rep["jobs"]]
    assert all(e["step"] == 0 for e in rep["jobs"])

    def agg(contributors, deltas):
        return server._handle({"cmd": "update", "id": "r",
                               "deltas": deltas,
                               "contributors": contributors})

    m = {"loss": 1.0, "n_err": 0}
    # fresh single-contributor aggregate: applies, clock ticks
    assert agg([{"id": "a", "job_id": jids[0], "delta": True,
                 "step": 0, "metrics": m}], _delta(shapes))["ok"]
    assert server.apply_step == 1
    # exactly at the bound (s = 1): applies
    assert agg([{"id": "b", "job_id": jids[1], "delta": True,
                 "step": 0, "metrics": m}], _delta(shapes))["ok"]
    assert server.apply_step == 2
    # one contributor past the bound (s = 2) + a fresh delta-bearing
    # sibling + a fresh delta-less eval: the whole aggregate refused
    before = _params(wf)
    rep = agg([{"id": "c", "job_id": jids[2], "delta": True,
                "step": 0, "metrics": m},
               {"id": "d", "job_id": jids[3], "delta": True,
                "step": 2, "metrics": m},
               {"id": "e", "job_id": jids[4], "metrics": m}],
              _delta(shapes))
    assert rep["ok"] is False and rep.get("stale_refused")
    assert rep["outcomes"][jids[2]] == "stale_refused"
    assert rep["outcomes"][jids[3]] == "requeued"
    assert rep["outcomes"][jids[4]] == "requeued"
    assert server.stale_refused == 1
    assert server.jobs_requeued == 2
    assert server.apply_step == 2               # nothing landed
    _assert_params(wf, before)
    assert len(server._pending) == 3
    assert all("_bad_replies" not in j for j in server._pending)
    ledger = server.jobs_ledger()
    assert ledger["balanced"] and ledger["dispatched"] == 5, ledger
    # per-leaf staleness histograms saw the manifest stamps
    summary = server.staleness_summary()
    assert summary["c"]["max"] == 2 and summary["d"]["max"] == 0


# -- quorum gate + degraded readiness -------------------------------------------


def test_quorum_gate_and_degraded_readiness(tmp_path):
    """Below ``min_slaves`` the master answers job requests with wait
    (degraded); relays' ``leaves`` reports count through the tree; the
    web_status readiness endpoint 503s exactly while degraded."""
    import json
    import urllib.error
    import urllib.request

    from znicz_tpu.server import Server
    from znicz_tpu.web_status import WebStatus

    wf = _make_workflow(tmp_path / "m")
    server = Server(wf, min_slaves=2)
    assert server._handle({"cmd": "register", "id": "s1",
                           **_handshake_fields(wf)})["ok"]
    rep = server._handle({"cmd": "job", "id": "s1"})
    assert rep == {"wait": True, "degraded": True, "members": 1,
                   "min_slaves": 2}
    assert server.degraded() and not server.quorum_met()

    status = WebStatus(port=0).start()
    try:
        status.register(wf)
        status.register_server(server)
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/readyz")
        assert e.value.code == 503
        body = json.loads(e.value.read())
        assert "degraded" in body["reason"] and body["members"] == 1

        # a relay's subtree leaf report lifts the count over the gate
        assert server._handle({"cmd": "register", "id": "r1",
                               "relay": True,
                               "bind": "tcp://127.0.0.1:9",
                               **_handshake_fields(wf)})["ok"]
        rep = server._handle({"cmd": "job", "id": "r1", "count": 2,
                              "leaves": 1})
        assert "jobs" in rep                    # 1 direct + 1 leaf = 2
        assert server.member_count() == 2 and not server.degraded()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/readyz") as r:
            assert json.load(r)["ready"] is True
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/status.json") as r:
            ela = json.load(r)["master"]["elastic"]
        assert ela["min_slaves"] == 2 and ela["members"] == 2
        assert ela["degraded"] is False
        assert ela["tree_plan"]["relays"][0]["id"] == "r1"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/") as r:
            assert "elastic:" in r.read().decode()
    finally:
        status.stop()


# -- resume round trip of the elastic accounting --------------------------------


def test_elastic_counters_resume_roundtrip(tmp_path):
    """A master crash mid-degraded-mode restores EXACT elastic books:
    the four ISSUE 11 counters and the apply-step staleness clock ride
    ``save_resume``/``restore_resume``."""
    from znicz_tpu.server import Server

    wf = _make_workflow(tmp_path / "m")
    server = Server(wf, staleness_bound=2, staleness_weight=True)
    server._m["stale_refused"].inc(3)
    server._m["weighted_applies"].inc(5)
    server._m["replans"].inc(2)
    server._m["preemptions_ridden"].inc(4)
    server._apply_step = 17
    path = str(tmp_path / "resume.pickle")
    server.save_resume(path)

    server2 = Server(_make_workflow(tmp_path / "m2"), resume_path=path)
    assert server2.resumed
    assert server2.stale_refused == 3
    assert server2.weighted_applies == 5
    assert server2.replans == 2
    assert server2.preemptions_ridden == 4
    assert server2.apply_step == 17


# -- runtime re-planner + orphan rehoming ---------------------------------------


def test_replan_and_orphan_leaf_rehoming(tmp_path):
    """Relay membership changes re-plan the tree at RUNTIME: joins and
    TTL evictions each recompute the plan (and count a ridden
    preemption); with ``elastic_rehome`` on, an orphan leaf registering
    directly is handed a recently-seen relay's bind, round-robin —
    never a stale one."""
    from znicz_tpu.server import Server

    wf = _make_workflow(tmp_path / "m")
    server = Server(wf, elastic_rehome=True, slave_ttl=60.0)
    hs = _handshake_fields(wf)
    b1, b2 = "tcp://127.0.0.1:21001", "tcp://127.0.0.1:21002"
    assert server._handle({"cmd": "register", "id": "r1", "relay": True,
                           "bind": b1, **hs})["ok"]
    assert server.replans == 1
    assert server._handle({"cmd": "register", "id": "r2", "relay": True,
                           "bind": b2, **hs})["ok"]
    assert server.replans == 2
    assert [r["id"] for r in server.tree_plan["relays"]] == ["r1", "r2"]
    # a re-register of a LIVE relay changes nothing: no re-plan
    assert server._handle({"cmd": "register", "id": "r2", "relay": True,
                           "bind": b2, **hs})["ok"]
    assert server.replans == 2

    rep = server._handle({"cmd": "register", "id": "s1", **hs})
    assert rep["rehome"] in (b1, b2)
    # relays are never rehomed
    assert "rehome" not in server._handle(
        {"cmd": "register", "id": "r1", "relay": True, "bind": b1, **hs})

    # TTL eviction of a relay: re-plan + a ridden preemption
    server.slaves["r1"] = time.time() - 120
    server._evict_dead_slaves()
    assert "r1" not in server.slaves
    assert server.replans == 3
    assert server.preemptions_ridden >= 1
    assert [r["id"] for r in server.tree_plan["relays"]] == ["r2"]
    assert server._handle({"cmd": "register", "id": "s2",
                           **hs})["rehome"] == b2
    # a relay silent past the recency window is not a safe target
    server.slaves["r2"] = time.time() - 11
    assert "rehome" not in server._handle(
        {"cmd": "register", "id": "s3", **hs})


# -- relay upstream re-homing: runtime tree healing -----------------------------


def test_relay_upstream_rehome_heals_tree(tmp_path):
    """A leaf relay whose mid-tier upstream dies re-homes one rung up
    (the upstream the mid advertised at register time), re-registers,
    and its subtree finishes the run — previously the whole subtree
    went silent behind a dead fallback chain."""
    from znicz_tpu.client import Client
    from znicz_tpu.parallel.relay import Relay
    from znicz_tpu.server import Server

    master_ep = "tcp://127.0.0.1:17670"
    mid_ep = "tcp://127.0.0.1:17671"
    leaf_ep = "tcp://127.0.0.1:17672"
    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, endpoint=master_ep, job_timeout=4.0)
    server_thread = threading.Thread(target=server.serve, daemon=True)
    server_thread.start()
    mid = Relay(master_ep, mid_ep, relay_id="heal-mid").start()
    leaf = Relay(mid_ep, leaf_ep, relay_id="heal-leaf",
                 recv_timeout=0.5, max_reconnects=2).start()
    slave = Client(_make_workflow(tmp_path / "s"), endpoint=leaf_ep,
                   slave_id="heal-s0")
    errors = []

    def worker():
        try:
            slave.run(recv_timeout=1.0, max_reconnects=60,
                      backoff_base=0.05, backoff_cap=0.3,
                      connect_retries=60)
        except BaseException as e:
            errors.append(repr(e))
            raise

    t = threading.Thread(target=worker, daemon=True)
    try:
        t.start()
        deadline = time.time() + 60
        while server.jobs_done < 2 and time.time() < deadline:
            time.sleep(0.05)
        assert server.jobs_done >= 2
        mid.stop()                      # the mid tier dies for good
        server_thread.join(timeout=120)
        assert not server_thread.is_alive()
        t.join(timeout=60)
        assert not errors, errors
        assert not t.is_alive()
    finally:
        mid.stop()
        leaf.stop()
    assert bool(master_wf.decision.complete)
    stats = leaf.stats()
    assert stats["upstream"] == master_ep   # re-homed one rung up
    assert stats["rehomes"] >= 1
    assert server.jobs_by_slave.get("heal-s0", 0) > 0
    assert server.jobs_done == sum(server.jobs_by_slave.values())
    assert server.jobs_ledger()["balanced"], server.jobs_ledger()


# -- seeded preemption schedule + driver ----------------------------------------


def test_preempt_schedule_and_subtree_driver():
    """The preemption timetable is a pure function of (seed, target) on
    its own salted stream (wire decisions untouched); the driver
    executes kill-then-restart per target, records wall-timed events,
    and exposes the kill window a progress gate holds counters to."""
    from znicz_tpu.parallel.chaos import FaultSchedule, SubtreePreempter

    a, b = FaultSchedule(9, drop=0.1), FaultSchedule(9, drop=0.1)
    assert [a.decide_preempt(i) for i in range(8)] == \
        [b.decide_preempt(i) for i in range(8)]
    assert FaultSchedule(10).decide_preempt(0) != a.decide_preempt(0)
    # independence: using the preempt stream never perturbs the wire one
    assert a.decisions(16) == FaultSchedule(9, drop=0.1).decisions(16)
    for i in range(8):
        k, d = a.decide_preempt(i, kill_s=(0.5, 2.0), down_s=(1.0, 3.0))
        assert 0.5 <= k <= 2.0 and 1.0 <= d <= 3.0

    log = []
    lock = threading.Lock()

    def act(kind, i):
        with lock:
            log.append((kind, i))

    targets = [(f"t{i}",
                (lambda i=i: act("kill", i)),
                (lambda i=i: act("restart", i))) for i in range(2)]
    pre = SubtreePreempter(FaultSchedule(3), targets,
                           kill_s=(0.01, 0.05), down_s=(0.02, 0.08))
    pre.start()
    assert pre.join(20)
    assert pre.preemptions == 2
    assert sorted(log) == [("kill", 0), ("kill", 1),
                           ("restart", 0), ("restart", 1)]
    for i in range(2):                  # killed before restarted
        assert log.index(("kill", i)) < log.index(("restart", i))
    events = pre.events
    assert len(events) == 4
    lo, hi = pre.window()
    assert lo <= hi
    assert lo == min(t for t, _, act_ in events if act_ == "kill")


# -- the slow preemption soak ---------------------------------------------------


@pytest.mark.slow
def test_preemption_soak_rides_out_subtree_kill(tmp_path):
    """Spot/preempt end to end: a seeded SubtreePreempter kills a relay
    plus its two slaves mid-run and restarts them; training completes
    in the quality band, apply progress continues DURING the kill
    window, the re-planner and preemption counters tick, and the job
    ledger balances — no gradient lost or double-applied."""
    from znicz_tpu.client import Client
    from znicz_tpu.parallel.chaos import (FaultSchedule, RelayHarness,
                                          SubtreePreempter)
    from znicz_tpu.parallel.relay import plan_tree
    from znicz_tpu.server import Server

    master_ep = "tcp://127.0.0.1:17680"
    plan = plan_tree(4, 2, master_ep, base_port=17681)
    # a LONG enough run that the whole kill window (kill + ~3s down +
    # TTL eviction at 1s) sits INSIDE training on a fast host; the
    # denser stream needs a calmer lr — at the sample's default 0.1,
    # 4 fully-async replicas over 20 minibatches/epoch diverge with or
    # without the elastic knobs (restored below: config leaks across
    # tests)
    from znicz_tpu.samples import mnist  # noqa: F401 -- the import
    # applies the sample's config DEFAULTS; reading prev_lr before it
    # would capture None and the restore below would poison the tree
    prev_lr = root.mnist.get("learning_rate")
    root.mnist.learning_rate = 0.03
    master_wf = _make_workflow(tmp_path / "m", max_epochs=6,
                               n_train=1200)
    # job_timeout is the reap CEILING: it must sit well inside the
    # down window, or the epoch tail (which waits on the dead
    # subtree's in-flight jobs) stalls the LIVE subtree past restart
    server = Server(master_wf, endpoint=master_ep, job_timeout=2.5,
                    slave_ttl=1.0, min_slaves=1,
                    staleness_bound=20, staleness_weight=True)
    server_thread = threading.Thread(
        target=server.serve, kwargs={"linger": 6.0}, daemon=True)
    server_thread.start()
    harnesses = [RelayHarness(r["upstream"], r["bind"],
                              relay_id=f"soak-r{i}", recv_timeout=1.0,
                              max_reconnects=60, child_ttl=1.5)
                 for i, r in enumerate(plan["relays"])]
    for h in harnesses:
        h.start()
    wfs = [_make_workflow(tmp_path / f"s{i}", max_epochs=6,
                          n_train=1200) for i in range(4)]
    clients = [Client(wfs[i], endpoint=plan["slave_endpoints"][i],
                      slave_id=f"pre{i}") for i in range(4)]
    errors, threads = [], {}

    def start_slave(i):
        def worker(c):
            try:
                c.run(recv_timeout=1.0, max_reconnects=80,
                      backoff_base=0.05, backoff_cap=0.4,
                      connect_retries=80)
            except BaseException as e:
                errors.append((c.slave_id, repr(e)))
                raise
        t = threading.Thread(target=worker, args=(clients[i],),
                             daemon=True)
        threads[i] = t
        t.start()

    for i in range(4):
        start_slave(i)
    sub_bind = plan["relays"][0]["bind"]
    sub_slaves = [i for i, ep in enumerate(plan["slave_endpoints"])
                  if ep == sub_bind]
    assert len(sub_slaves) == 2
    marks = {}

    def kill():
        for i in sub_slaves:
            clients[i].preempt()
        for i in sub_slaves:
            threads[i].join(timeout=10)
        harnesses[0].kill()
        marks["kill"] = (server.jobs_done, server.aggregated_updates,
                         server.weighted_applies)

    def restart():
        marks["restart"] = (server.jobs_done, server.aggregated_updates,
                            server.weighted_applies)
        harnesses[0].start()
        for i in sub_slaves:
            clients[i] = Client(wfs[i],
                                endpoint=plan["slave_endpoints"][i],
                                slave_id=f"pre{i}")
            start_slave(i)

    preempter = SubtreePreempter(
        FaultSchedule(23), [("subtree-0", kill, restart)],
        kill_s=(0.1, 0.3), down_s=(4.5, 5.5))
    deadline = time.time() + 120
    while server.jobs_done < 6 and time.time() < deadline:
        time.sleep(0.05)
    assert server.jobs_done >= 6
    preempter.start()                   # seeded kill, anchored mid-run
    try:
        assert preempter.join(60)
        server_thread.join(timeout=300)
        assert not server_thread.is_alive()
        for t in list(threads.values()):
            t.join(timeout=60)
        assert not errors, errors
        assert not any(t.is_alive() for t in threads.values())
    finally:
        root.mnist.learning_rate = prev_lr
        preempter.stop()
        for h in harnesses:
            try:
                h.kill(timeout=5)
            except Exception:
                pass
    dec = master_wf.decision
    assert bool(dec.complete)
    valid = dec.epoch_metrics[1]
    assert valid is not None and valid["err_pct"] < 70.0, valid
    assert preempter.preemptions == 1
    # apply progress CONTINUED while half the fleet was down
    k, r = marks["kill"], marks["restart"]
    assert r[0] > k[0], (k, r)          # jobs kept completing
    assert r[1] > k[1] or r[2] > k[2]   # aggregated/weighted applies
    # the elastic machinery really engaged
    assert server.preemptions_ridden >= 1
    assert server.replans >= 1
    assert server.reregistrations >= 1
    assert server.weighted_applies > 0
    # exact accounting after preemption + re-plan: nothing lost or
    # double-applied
    ledger = server.jobs_ledger()
    assert ledger["balanced"], ledger
    assert ledger["quarantined_updates"] == 0
    assert server.jobs_done == sum(server.jobs_by_slave.values())
    assert set(server.jobs_by_slave) <= {f"pre{i}" for i in range(4)}
