"""Loader state-machine semantics (reference: veles/loader/base.py tests)."""

import numpy as np

from znicz_tpu.loader.base import TEST, TRAIN, VALID
from znicz_tpu.loader.fullbatch import FullBatchLoader, FullBatchLoaderMSE
from znicz_tpu.normalization import LinearNormalizer, MeanDispNormalizer


def make_loader(n_test=4, n_valid=6, n_train=10, mb=4, **kw):
    ld = FullBatchLoader(name="ld", minibatch_size=mb, **kw)
    total = n_test + n_valid + n_train
    ld.original_data.mem = np.arange(total * 3, dtype=np.float32).reshape(
        total, 3)
    ld.original_labels.mem = np.arange(total, dtype=np.int32) % 5
    ld.class_lengths = [n_test, n_valid, n_train]
    ld.initialize(device=None)
    return ld


def test_epoch_walk_classes_and_tails():
    ld = make_loader()
    seen = []
    for _ in range(6):   # 4/4 test=1 batch, 6/4 valid=2, 10/4 train=3
        ld.run()
        seen.append((ld.minibatch_class, ld.minibatch_size,
                     ld.class_ended, ld.last_minibatch))
    assert seen[0] == (TEST, 4, True, False)
    assert seen[1] == (VALID, 4, False, False)
    assert seen[2] == (VALID, 2, True, False)        # short tail, no straddle
    assert seen[3] == (TRAIN, 4, False, False)
    assert seen[5] == (TRAIN, 2, True, True)         # epoch tail
    assert ld.epoch_number == 0                      # increments on next run
    ld.run()
    assert ld.minibatch_class == TEST                # next epoch restarts
    assert ld.epoch_number == 1


def test_indices_cover_each_class_exactly_once():
    ld = make_loader()
    got = {TEST: [], VALID: [], TRAIN: []}
    for _ in range(6):
        ld.run()
        idx = np.array(ld.minibatch_indices.map_read())[:ld.minibatch_size]
        got[ld.minibatch_class].extend(idx.tolist())
    assert sorted(got[TEST]) == list(range(0, 4))
    assert sorted(got[VALID]) == list(range(4, 10))
    assert sorted(got[TRAIN]) == list(range(10, 20))


def test_train_reshuffles_between_epochs_but_not_eval():
    ld = make_loader(mb=10)
    orders = []
    for _ in range(3):   # 3 epochs of [test(1) valid(1) train(1)] @ mb=10
        epoch = []
        while True:
            ld.run()
            if ld.minibatch_class == TRAIN:
                epoch.append(
                    np.array(ld.minibatch_indices.mem)[:ld.minibatch_size]
                    .copy())
            if ld.last_minibatch:
                break
        orders.append(np.concatenate(epoch))
    assert not np.array_equal(orders[0], orders[1])  # reshuffled
    assert sorted(orders[0]) == sorted(orders[1])    # same index set


def test_minibatch_data_gather_matches_indices():
    ld = make_loader()
    ld.run()
    idx = np.array(ld.minibatch_indices.mem)
    data = np.array(ld.minibatch_data.map_read())
    np.testing.assert_allclose(data, ld.original_data.mem[idx])
    labels = np.array(ld.minibatch_labels.map_read())
    np.testing.assert_array_equal(labels, ld.original_labels.mem[idx])


def test_mse_loader_targets_from_data():
    ld = FullBatchLoaderMSE(name="ldmse", minibatch_size=3,
                            targets_from_data=True)
    ld.original_data.mem = np.random.default_rng(0).normal(
        size=(9, 4)).astype(np.float32)
    ld.class_lengths = [0, 3, 6]
    ld.initialize(device=None)
    ld.run()
    np.testing.assert_allclose(np.array(ld.minibatch_targets.map_read()),
                               np.array(ld.minibatch_data.map_read()))


def test_linear_normalizer_fit_applied_on_train_only():
    norm = LinearNormalizer()
    ld = make_loader(normalizer=norm)
    data = ld.original_data.map_read()
    # fitted on train rows only (values 30..59), applied to all
    assert norm.vmin == 30.0 and norm.vmax == 59.0
    assert data.max() > 1.0 - 1e-6   # train max maps to 1
    rt = {}
    norm2 = LinearNormalizer()
    norm2.restore(norm.state())
    assert norm2.vmin == norm.vmin


def test_mean_disp_normalizer_roundtrip():
    rng = np.random.default_rng(1)
    data = rng.normal(2.0, 3.0, size=(50, 8)).astype(np.float32)
    norm = MeanDispNormalizer()
    norm.fit(data)
    d2 = data.copy()
    norm.apply_inplace(d2)
    assert abs(d2.mean()) < 1e-5
    norm2 = MeanDispNormalizer()
    norm2.restore(norm.state())
    d3 = data.copy()
    norm2.apply_inplace(d3)
    np.testing.assert_allclose(d2, d3)


def test_native_shuffle_path():
    from znicz_tpu import native

    if not native.available():
        import pytest
        pytest.skip("native lib unavailable")
    ld = make_loader(native_shuffle=True)
    idx = []
    for _ in range(6):
        ld.run()
        if ld.minibatch_class == TRAIN:
            idx.extend(np.array(ld.minibatch_indices.mem)
                       [:ld.minibatch_size].tolist())
    assert sorted(idx) == list(range(10, 20))


def test_class_balanced_training_segment():
    """balance_classes=True (SURVEY Loader-base row): each epoch's TRAIN
    segment gives every label an equal share of slots, oversampling
    minorities with replacement; reshuffles per epoch; eval splits
    untouched."""
    import numpy as np

    from znicz_tpu.loader.base import TRAIN, VALID
    from znicz_tpu.loader.fullbatch import FullBatchLoader

    class Imbalanced(FullBatchLoader):
        def load_data(self):
            n_valid, n_train = 20, 200
            labels = np.zeros(n_valid + n_train, np.int32)
            labels[n_valid:] = (np.arange(n_train) < 180).astype(np.int32)
            # class 1: 180 train samples, class 0: only 20 -> minority
            self.original_data.mem = np.random.default_rng(0).normal(
                size=(n_valid + n_train, 4)).astype(np.float32)
            self.original_labels.mem = labels
            self.class_lengths = [0, n_valid, n_train]
            super().load_data()

    loader = Imbalanced(name="bal", minibatch_size=20,
                        balance_classes=True)
    loader.initialize(device=None)

    def epoch_train_indices():
        got = []
        while True:
            loader.run()
            if loader.minibatch_class == TRAIN:
                idx = np.array(loader.minibatch_indices.mem)
                got.append(idx[:loader.minibatch_size].copy())
            if loader.last_minibatch:
                return np.concatenate(got)

    labels_all = np.asarray(loader.original_labels.mem)
    epochs = [epoch_train_indices() for _ in range(8)]
    for ep in epochs:
        counts = np.bincount(labels_all[ep], minlength=2)
        assert counts.sum() == 200
        assert abs(counts[0] - counts[1]) <= 2, counts   # balanced
    # epochs genuinely reshuffle (index sequences differ)
    assert not np.array_equal(epochs[0], epochs[1])
    # and every epoch resamples from the FULL canonical population —
    # resampling from the previous epoch's output would lose ~37% of
    # distinct majority-class samples per epoch, compounding
    majority = np.arange(20, 220)[labels_all[20:220] == 1]
    seen_late = set(np.unique(epochs[-1])) & set(majority.tolist())
    assert len(seen_late) > 0.55 * 100, len(seen_late)

    # default (no balancing) keeps the raw distribution
    from znicz_tpu.core import prng as _prng

    _prng.reset(1013)
    plain = Imbalanced(name="plain", minibatch_size=20)
    plain.initialize(device=None)
    loader = plain
    counts = np.bincount(labels_all[epoch_train_indices()], minlength=2)
    assert counts[1] == 180 and counts[0] == 20
