"""Replica-fleet balancer (ISSUE 12): TTL'd heartbeat membership,
least-loaded dispatch, exactly-once failover, hedged retries, canary
rollover with auto-rollback + healing, the per-endpoint client breaker,
the aggregate /readyz + fleet panel, and the ChaosProxy soak (lean in
tier-1; the full soak rides the ``slow`` marker).

Most tests run against :class:`ScriptedReplica` — the model-free fake
replica harness (parallel/chaos.py) that speaks the replica protocol
(heartbeats, swap/rollback, replica_id-stamped replies) with a scripted
``y = x * scale(generation)`` forward, so fleet semantics are proven
without paying a single jit warmup.  One test runs a REAL
``InferenceServer`` replica end-to-end to pin the frontend's heartbeat/
stamp integration."""

import json
import time
import urllib.request

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.config import root


X1 = np.arange(4, dtype=np.float32).reshape(1, 4) + 1.0


def _fleet(n=2, snapshots=None, bal_kwargs=None, rep_kwargs=None):
    """A started balancer + n started scripted replicas."""
    from znicz_tpu.parallel.chaos import ScriptedReplica
    from znicz_tpu.serving import ReplicaBalancer

    kwargs = dict(replica_ttl_s=1.0, heartbeat_s=0.25,
                  failover_timeout_s=0.5, failover_tries=4,
                  hedge_floor_s=0.25, canary_requests=6,
                  parity_every=2, canary_timeout_s=20.0)
    kwargs.update(bal_kwargs or {})
    bal = ReplicaBalancer(**kwargs).start()
    reps = [ScriptedReplica(bal.endpoint, f"r{i}",
                            snapshots=dict(snapshots or {}),
                            **(rep_kwargs or {})).start()
            for i in range(n)]
    t0 = time.time()
    while bal.ready_count() < n:
        assert time.time() - t0 < 10, "fleet never became ready"
        time.sleep(0.02)
    return bal, reps


def _client(bal, **kw):
    from znicz_tpu.serving import InferenceClient

    kw.setdefault("timeout", 10.0)
    kw.setdefault("breaker_failures", 0)
    kw.setdefault("resend_after_s", 30.0)   # balancer failover, not
    # client resends, is under test — resends would mask lost replies
    return InferenceClient(bal.endpoint, **kw)


def _drive_until(cli, pred, budget=15.0, x=X1):
    t0 = time.time()
    while time.time() - t0 < budget:
        for _ in range(4):
            cli.result(cli.submit(x), timeout=8)
        if pred():
            return True
    return False


def _teardown(bal, reps, *clis):
    for c in clis:
        c.close()
    bal.stop()
    for r in reps:
        r.kill()


# -- membership + dispatch -----------------------------------------------------


def test_heartbeat_membership_ttl_and_spread():
    bal, reps = _fleet(2)
    cli = _client(bal)
    try:
        for _ in range(16):
            rep = cli.result(cli.submit(X1))
            # the balancer stamp + the replica stamp + the generation,
            # on every reply (the client breaker and A/B attribution
            # ride these)
            assert rep.get("lb") is True
            assert rep["replica_id"] in ("r0", "r1")
            assert rep["gen"] == 1
            assert np.array_equal(rep["y"], X1)
        # least-loaded over two idle replicas spreads the work
        assert reps[0].served > 0 and reps[1].served > 0
        st = bal.stats()
        assert st["total_replicas"] == 2 and st["ready_replicas"] == 2
        row = st["replicas"][0]
        for key in ("gen", "queue_depth", "in_flight",
                    "last_heartbeat_s", "snapshot_path",
                    "p99_ms_by_bucket"):
            assert key in row
        # TTL eviction: a silent replica leaves the membership
        reps[0].kill()
        t0 = time.time()
        while bal.member_count() > 1:
            assert time.time() - t0 < 10
            time.sleep(0.05)
        assert bal.replicas_lost == 1
        # ... and the survivor serves alone
        assert cli.result(cli.submit(X1))["replica_id"] == "r1"
        assert bal.ledger()["balanced"]
    finally:
        _teardown(bal, reps, cli)


def test_exactly_once_failover_through_a_blackhole():
    """A replica that accepts requests and never answers: the balancer
    re-dispatches the SAME bytes after its failover timeout, and every
    request is answered exactly once — no double delivery, no
    silence."""
    import collections

    bal, reps = _fleet(2, bal_kwargs={"hedge": False},
                       rep_kwargs={})
    reps[0].kill()
    from znicz_tpu.parallel.chaos import ScriptedReplica

    hole = ScriptedReplica(bal.endpoint, "hole", blackhole=True).start()
    reps[0] = hole
    while bal.member_count() < 2 or "hole" not in {
            m["replica_id"] for m in bal.stats()["replicas"]}:
        time.sleep(0.02)
    cli = _client(bal)
    try:
        rids = [cli.submit(X1) for _ in range(10)]
        got = collections.Counter()
        t0 = time.time()
        while sum(got.values()) < 10 and time.time() - t0 < 12:
            for rep in cli.collect(0.05):
                got[rep["req_id"]] += 1
                assert rep["ok"], rep
        assert sorted(got) == sorted(rids)
        assert max(got.values()) == 1          # exactly once
        assert bal.failovers > 0
        assert hole.swallowed > 0              # the hole really ate some
        assert bal.ledger()["balanced"]
    finally:
        _teardown(bal, reps, cli)


def test_hedged_retries_race_the_tail():
    """One replica stalls every 2nd request well past the hedge delay:
    the hedge races a second replica, the first reply wins, the loser
    is deduped — tail latency is bounded by the race, not the stall."""
    bal, reps = _fleet(1, bal_kwargs={"hedge_floor_s": 0.1,
                                      "failover_timeout_s": 3.0,
                                      "replica_ttl_s": 3.0},
                       rep_kwargs={"stall_s": 0.7, "stall_every": 2})
    from znicz_tpu.parallel.chaos import ScriptedReplica

    fast = ScriptedReplica(bal.endpoint, "fast").start()
    reps.append(fast)
    while bal.ready_count() < 2:
        time.sleep(0.02)
    cli = _client(bal)
    try:
        lats = []
        for _ in range(20):
            t0 = time.time()
            rep = cli.result(cli.submit(X1), timeout=8)
            lats.append(time.time() - t0)
            assert np.array_equal(rep["y"], X1)
        assert bal.hedges > 0 and bal.hedge_wins > 0
        assert bal.dup_replies_dropped > 0     # the stalled loser lands
        # late and is deduped, never double-delivered
        assert max(lats) < 0.7                 # the race beat the stall
        assert bal.ledger()["balanced"]
    finally:
        _teardown(bal, reps, cli)


# -- canary rollover (promote / heal / auto-rollback) --------------------------


def test_canary_rollover_promote_heal_and_regression_rollback():
    snaps = {"same": 1.0, "diff": 3.0}
    bal, reps = _fleet(3, snapshots=snaps)
    cli = _client(bal)
    try:
        # (1) healthy wave: same params under a new path -> parity
        # probes agree, p99 in band, fleet promotes canary -> full
        rep = cli.result(cli._send({"cmd": "swap", "path": "same"}))
        assert rep["ok"] and rep["swap_started"] and rep["canary"]
        assert _drive_until(cli, lambda: bal.rollovers == 1)
        assert bal.parity_checks > 0 and bal.parity_mismatches == 0
        assert bal.rollover_history[-1]["result"] == "promoted"
        gens = {cli.result(cli.submit(X1))["gen"] for _ in range(6)}
        assert gens == {2}
        assert bal.stats()["fleet_path"] == "same"
        # a second swap while one runs is refused readably
        from znicz_tpu.serving import InferenceError

        # (2) healing: a restarted replica boots with its boot snapshot
        # and an off-fleet generation; the balancer re-swaps it onto
        # the promoted path, restoring generation lockstep
        reps[0].kill()
        time.sleep(0.1)
        reps[0].restart()
        assert _drive_until(cli, lambda: bal.member_count() == 3 and all(
            m["gen"] == 2 and m["snapshot_path"] == "same"
            for m in bal.stats()["replicas"]))
        assert bal.heals == 1                  # debounced: exactly one
        # (3) forced regression: genuinely different params under an
        # expect-parity swap -> shadow probes mismatch -> auto-rollback,
        # losing generation's record preserved for the postmortem
        rep = cli.result(cli._send({"cmd": "swap", "path": "diff"}))
        assert rep["ok"]
        assert _drive_until(cli, lambda: bal.rollbacks == 1)
        record = bal.rollover_history[-1]
        assert record["result"] == "rolled_back"
        assert "parity" in record["reason"]
        assert record["parity_mismatches"] >= 1
        assert record["old_gen"] == 2 and record["new_gen"] == 3
        # the fleet still serves the OLD generation bit-exactly, stamp
        # included (ModelRunner.rollback restores the retained tuple)
        for _ in range(6):
            rep = cli.result(cli.submit(X1))
            assert rep["gen"] == 2
            assert np.array_equal(rep["y"], X1)
        assert bal.ledger()["balanced"]
    finally:
        _teardown(bal, reps, cli)


def test_canary_p99_regression_rolls_back():
    """The OTHER regression trigger: a new generation whose answers
    agree bit-exactly but arrive slow.  The scripted 'upgrade' stalls
    every reply 0.35s; with hedging off and the failover timeout above
    the stall, the canary's p99 blows the `canary_p99_mult` band and
    the wave rolls back — the fleet ends on the old (fast) generation,
    losing wave recorded with both p99s for the postmortem."""
    snaps = {"slow": {"scale": 1.0, "stall_s": 0.35}}
    bal, reps = _fleet(3, snapshots=snaps,
                       bal_kwargs={"hedge": False,
                                   "failover_timeout_s": 2.0,
                                   "canary_requests": 5,
                                   "canary_p99_mult": 3.0,
                                   "parity_every": 1000})
    cli = _client(bal, timeout=15.0)
    try:
        rep = cli.result(cli._send({"cmd": "swap", "path": "slow",
                                    "parity": False}))
        assert rep["ok"]
        assert _drive_until(cli, lambda: bal.rollbacks == 1, budget=25)
        record = bal.rollover_history[-1]
        assert record["result"] == "rolled_back"
        assert "p99" in record["reason"]
        assert record["canary_p99_ms"] > 3.0 * record["old_p99_ms"]
        gens = {cli.result(cli.submit(X1))["gen"] for _ in range(4)}
        assert gens == {1}                     # stamp restored too
        assert bal.ledger()["balanced"]
    finally:
        _teardown(bal, reps, cli)


def test_rollover_refused_below_health_floor():
    """No ready replicas / non-uniform generations refuse the wave
    readably instead of half-flipping a fleet."""
    from znicz_tpu.serving import InferenceError, ReplicaBalancer

    bal = ReplicaBalancer().start()
    cli = _client(bal)
    try:
        with pytest.raises(InferenceError, match="no ready replicas"):
            cli.result(cli._send({"cmd": "swap", "path": "x"}))
        with pytest.raises(InferenceError, match="needs a snapshot"):
            cli.result(cli._send({"cmd": "swap"}))
    finally:
        cli.close()
        bal.stop()


# -- per-endpoint client breaker (ISSUE 12 satellite) --------------------------


def test_client_breaker_is_per_endpoint_behind_a_balancer():
    """Service-scoped failures stamped with a replica_id by a balancer
    reply open THAT replica's window — never the whole-service breaker
    (the balancer is already routing around the sick replica)."""
    from znicz_tpu.serving import InferenceError

    # a 1-replica fleet whose replica sheds service-scoped, and a
    # failover budget of 1 so the shed is FORWARDED, not retried
    bal, reps = _fleet(1, bal_kwargs={"failover_tries": 1,
                                      "hedge": False},
                       rep_kwargs={"refuse": ("shed", "service")})
    cli = _client(bal, breaker_failures=3, breaker_window=6)
    try:
        for _ in range(5):
            with pytest.raises(InferenceError):
                cli.result(cli.submit(X1))
        # the sick replica's window opened; the service breaker did NOT
        assert cli.breaker_state == "closed"
        assert cli.breaker_state_for("r0") == "open"
        assert cli.replica_breaker_opens == 1
        assert cli.replica_breakers()["r0"]["failures"] >= 3
        cli.submit(X1)                         # no CircuitOpenError
    finally:
        _teardown(bal, reps, cli)


def test_client_breaker_still_global_against_a_direct_runner():
    """The same stamped refusals WITHOUT the balancer's ``lb`` stamp
    (a direct runner) keep feeding the whole-service breaker."""
    from znicz_tpu.parallel.chaos import ScriptedReplica
    from znicz_tpu.serving import (CircuitOpenError, InferenceClient,
                                   InferenceError)

    # the scripted replica doubles as a direct service: its replies
    # carry replica_id but no lb stamp
    from znicz_tpu.serving import ReplicaBalancer

    bal = ReplicaBalancer().start()     # just a heartbeat sink
    sick = ScriptedReplica(bal.endpoint, "sick",
                           refuse=("shed", "service")).start()
    cli = InferenceClient(sick.endpoint, timeout=5.0,
                          breaker_failures=3, breaker_window=6,
                          resend_after_s=30.0)
    try:
        opened = False
        for _ in range(8):
            try:
                cli.result(cli.submit(X1))
            except InferenceError:
                continue
            except CircuitOpenError:
                opened = True
                break
        assert opened or cli.breaker_state == "open"
        assert cli.breaker_opens >= 1
        assert cli.replica_breakers() == {}    # per-endpoint untouched
    finally:
        cli.close()
        sick.kill()
        bal.stop()


# -- aggregate readiness + fleet panel (ISSUE 12 satellite) --------------------


def test_web_status_aggregate_readyz_and_fleet_panel():
    from znicz_tpu.web_status import WebStatus

    bal, reps = _fleet(2, bal_kwargs={"min_replicas": 2})
    status = WebStatus(port=0).start()
    status.register_balancer(bal)
    base = f"http://127.0.0.1:{status.port}"

    def get(path):
        try:
            with urllib.request.urlopen(base + path) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    try:
        code, body = get("/readyz")
        ready = json.loads(body)
        assert code == 200 and ready["ready"]
        assert ready["ready_replicas"] == 2 and ready["total"] == 2
        assert ready["min_replicas"] == 2
        code, _ = get("/healthz")
        assert code == 200
        # the fleet panel: per-replica rows + the ledger line
        code, body = get("/status.json")
        snap = json.loads(body)
        rows = snap["balancer"]["replicas"]
        assert {r["replica_id"] for r in rows} == {"r0", "r1"}
        assert all("last_heartbeat_s" in r and "gen" in r for r in rows)
        assert snap["balancer"]["ledger"]["balanced"]
        _, html_body = get("/")
        assert "Replica fleet" in html_body
        # below quorum: the AGGREGATE goes 503 (one process dying would
        # never have flipped the old per-process answer)
        reps[0].kill()
        t0 = time.time()
        while True:
            code, body = get("/readyz")
            if code == 503:
                break
            assert time.time() - t0 < 10
            time.sleep(0.05)
        assert "below the min_replicas quorum" in json.loads(
            body)["reason"]
    finally:
        status.stop()
        _teardown(bal, reps)


# -- real-replica integration --------------------------------------------------


def _tiny_wf():
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 120
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def test_real_replica_announces_and_serves_through_balancer():
    """One REAL InferenceServer behind the balancer: the frontend's
    heartbeat loop registers membership, piggybacks per-bucket p99, and
    stamps replica_id/gen on replies the balancer forwards."""
    from znicz_tpu.serving import InferenceServer, ReplicaBalancer

    from znicz_tpu.serving import InferenceClient

    bal = ReplicaBalancer(replica_ttl_s=2.0).start()
    srv = InferenceServer(_tiny_wf(), max_batch=4, max_delay_ms=1.0,
                          announce=bal.endpoint,
                          replica_id="real-0").start()
    cli = InferenceClient(bal.endpoint, timeout=20.0,
                          breaker_failures=0)
    try:
        t0 = time.time()
        while bal.ready_count() < 1:
            assert time.time() - t0 < 20
            time.sleep(0.05)
        x = np.zeros((1, 28 * 28), np.float32)
        direct = srv.runner.infer(srv.runner.pad(x, 1))[:1]
        for _ in range(5):
            rep = cli.result(cli.submit(x))
            assert rep["lb"] and rep["replica_id"] == "real-0"
            assert rep["gen"] == 1
            # through-the-balancer result == the runner's own forward
            assert np.array_equal(rep["y"], direct)
        assert srv.heartbeats_out > 0
        member = bal.stats()["replicas"][0]
        assert member["replica_id"] == "real-0"
        # per-bucket p99 telemetry piggybacked once traffic flowed
        t0 = time.time()
        while not member["p99_ms_by_bucket"]:
            assert time.time() - t0 < 10
            time.sleep(0.1)
            member = bal.stats()["replicas"][0]
        assert 1 in member["p99_ms_by_bucket"]  # rung-1 latencies
        # rollback is a REPLICA control command (the balancer's wave
        # machinery sends it over the data plane); with nothing
        # retained it is a readable refusal
        from znicz_tpu.serving import InferenceClient, InferenceError

        direct = InferenceClient(srv.endpoint, timeout=10.0,
                                 breaker_failures=0)
        try:
            with pytest.raises(InferenceError,
                               match="no previous generation"):
                direct.result(direct._send({"cmd": "rollback"}))
        finally:
            direct.close()
        assert bal.ledger()["balanced"]
    finally:
        cli.close()
        srv.stop()
        bal.stop()


# -- autoscaler (ISSUE 17) -----------------------------------------------------


def test_autoscaler_spawns_to_cap_and_drains_back_to_quorum():
    """The elasticity control loop over scripted replicas: a forced
    'high' band spawns through the FleetScaler up to ``autoscale_max``
    (pending-spawn reservations stop over-spawn at the cap), then a
    forced 'low' band drains-then-retires back down to — and never
    below — the ``min_replicas`` quorum, with traffic served and the
    ledger balanced throughout."""
    from znicz_tpu.parallel.chaos import FleetScaler, ScriptedReplica

    bal, reps = _fleet(2, bal_kwargs=dict(min_replicas=2))
    scaler = FleetScaler(
        lambda i: ScriptedReplica(bal.endpoint, f"s{i}"))
    for r in reps:
        scaler.adopt(r)
    cli = _client(bal)
    try:
        # high_load < 0 forces every eval 'high' — a deterministic ramp
        bal.enable_autoscale(
            scaler.spawn, scaler.retire, autoscale_max=4,
            autoscale_high_load=-1.0, autoscale_low_load=-2.0,
            autoscale_up_after=2, autoscale_down_after=2,
            autoscale_eval_s=0.05, autoscale_cooldown_s=0.05,
            autoscale_drain_timeout_s=5.0)
        t0 = time.time()
        while bal.member_count() < 4:
            assert time.time() - t0 < 15, "never scaled to the cap"
            time.sleep(0.02)
        assert bal.scale_ups >= 2
        st = bal.stats()["autoscale"]
        assert st["enabled"] and st["max"] == 4
        # at the cap: no spawns pile up past it
        time.sleep(0.3)
        assert bal.member_count() == 4
        assert scaler.counts["spawned"] == 2
        for _ in range(8):
            assert cli.result(cli.submit(X1))["lb"] is True
        # force 'low': drain-then-retire to the quorum, not past it.
        # Retired members are evicted immediately, but a last
        # heartbeat can race the kill and re-add one briefly — the
        # cooldown sits ABOVE the 1.0s replica TTL so even that
        # corpse is gone before the next decision
        bal.enable_autoscale(
            scaler.spawn, scaler.retire, autoscale_max=4,
            autoscale_high_load=1e9, autoscale_low_load=1e9,
            autoscale_cooldown_s=1.5)
        t0 = time.time()
        while bal.member_count() > 2:
            assert time.time() - t0 < 25, "never drained to quorum"
            time.sleep(0.05)
        time.sleep(0.5)
        assert bal.member_count() == 2          # quorum floor holds
        assert bal.scale_downs == 2
        assert scaler.counts["retired"] == 2
        assert not bal.stats()["autoscale"]["retiring"]
        assert cli.result(cli.submit(X1))["lb"] is True
        assert bal.ledger()["balanced"]
    finally:
        _teardown(bal, reps, cli)
        scaler.stop_all()


def test_scale_down_never_counts_a_healing_replica_as_capacity():
    """The ISSUE 17 satellite bugfix, as a regression test: a replica
    mid-heal is serving STALE params and about to swap — it must not
    count as servable capacity, or an idle band retires the last
    HEALTHY replica while the heal is still in flight.  With one of
    two replicas healing, the scale-down gate sees ONE servable
    replica and (min_replicas=1) refuses to act; the moment the heal
    clears, the same band drains exactly one."""
    from znicz_tpu.parallel.chaos import FleetScaler, ScriptedReplica

    bal, reps = _fleet(2, bal_kwargs=dict(min_replicas=1))
    scaler = FleetScaler(
        lambda i: ScriptedReplica(bal.endpoint, f"s{i}"))
    for r in reps:
        scaler.adopt(r)
    cli = _client(bal)
    try:
        with bal._lock:                 # r1 enters its heal window
            bal._healing["r1"] = time.time()
        bal.enable_autoscale(
            scaler.spawn, scaler.retire,
            autoscale_high_load=1e9, autoscale_low_load=1e9,
            autoscale_down_after=1, autoscale_eval_s=0.05,
            autoscale_cooldown_s=0.2)
        time.sleep(0.6)                 # many idle 'low' evals
        assert bal.scale_downs == 0 and bal.member_count() == 2
        st = bal.stats()
        assert st["autoscale"]["servable"] == 1
        rows = {r["replica_id"]: r for r in st["replicas"]}
        assert rows["r1"]["healing"] and not rows["r0"]["healing"]
        with bal._lock:                 # heal lands: r1 back on fleet
            bal._healing.pop("r1")
        t0 = time.time()
        while bal.member_count() > 1:
            assert time.time() - t0 < 15, "never drained post-heal"
            time.sleep(0.05)
        assert bal.scale_downs == 1
        assert cli.result(cli.submit(X1))["lb"] is True
        assert bal.ledger()["balanced"]
    finally:
        _teardown(bal, reps, cli)
        scaler.stop_all()


# -- chaos soak (ISSUE 12 satellite) -------------------------------------------


def _free_port_endpoint():
    """A concrete loopback endpoint: ChaosProxy does not expose a
    resolved wildcard bind."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"tcp://127.0.0.1:{port}"


def test_chaos_soak_lean():
    """Lean soak: proxy corruption/drop/dup/delay + one kill/restart."""
    _run_soak(_free_port_endpoint(), n_requests=50, kills=True,
              swap=False)


def _run_soak(front, n_requests, kills, swap):
    from znicz_tpu.parallel.chaos import ChaosProxy, FaultSchedule
    from znicz_tpu.serving import InferenceClient

    snaps = {"v2": 1.0}
    bal, reps = _fleet(2, snapshots=snaps,
                       bal_kwargs={"failover_timeout_s": 0.8,
                                   "replica_ttl_s": 1.5,
                                   "canary_requests": 4})
    schedule = FaultSchedule(seed=4242, drop=0.05, corrupt=0.05,
                             duplicate=0.05, delay=0.08,
                             delay_s=(0.02, 0.1))
    proxy = ChaosProxy(front, bal.endpoint, schedule).start()
    cli = InferenceClient(front, timeout=20.0, resend_after_s=0.5,
                          max_resends=30, breaker_failures=0)
    answered = {}
    try:
        swapped = False
        for i in range(n_requests):
            rid = cli.submit(X1)
            rep = cli.result(rid, timeout=15)
            assert rid not in answered      # client-visible exactly-once
            answered[rid] = rep
            assert np.array_equal(rep["y"], X1), (i, rep)
            if kills and i == n_requests // 3:
                reps[0].kill()
            if kills and i == 2 * n_requests // 3:
                reps[0].restart()
            if swap and not swapped and i == n_requests // 2:
                try:
                    cli.result(cli._send(
                        {"cmd": "swap", "path": "v2"}), timeout=15)
                except Exception:
                    pass                    # reply lost to chaos; the
                    # wave still runs server-side
                swapped = True
        assert len(answered) == n_requests
        assert bal.codec.bad_frames == proxy.counters["req"]["corrupt"]
        assert bal.ledger()["balanced"]
        return dict(bad_frames=bal.codec.bad_frames,
                    failovers=bal.failovers,
                    hedges=bal.hedges,
                    rollovers=bal.rollovers)
    finally:
        proxy.stop()
        _teardown(bal, reps, cli)


@pytest.mark.slow
def test_chaos_soak_full():
    """The full soak: more traffic, kill + restart racing hedges AND a
    rollover wave mid-chaos."""
    _run_soak(_free_port_endpoint(), n_requests=150, kills=True,
              swap=True)
