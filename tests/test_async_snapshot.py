"""Async / deep-pipeline checkpointing (VERDICT r4 item 4): the fast path
must snapshot WITHOUT stalling training — and the snapshot must be the
same checkpoint the synchronous writeback path would have produced, at
every level (weights, velocities, loader order, prng streams), so resume
trajectories are indistinguishable."""

import os

import numpy as np
import pytest

from znicz_tpu.core.config import root

from tests.test_fused import fresh_mnist


def _run_fused(wf, depth=1):
    from znicz_tpu.parallel.fused import FusedTrainer

    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    trainer = FusedTrainer(wf)
    trainer.pipeline_depth = depth
    trainer.run()
    return losses, trainer


def _load_snap(path):
    from znicz_tpu.snapshotter import Snapshotter

    return Snapshotter.load(path)


def _assert_snaps_equal(s1, s2, exact_arrays=True):
    assert set(s1["units"]) == set(s2["units"])
    for name in s1["units"]:
        for k in s1["units"][name]:
            a, b = s1["units"][name][k], s2["units"][name][k]
            if exact_arrays:
                np.testing.assert_array_equal(a, b, err_msg=f"{name}.{k}")
            else:
                np.testing.assert_allclose(
                    np.asarray(a, np.float32), np.asarray(b, np.float32),
                    rtol=1e-4, atol=1e-6, err_msg=f"{name}.{k}")
    assert set(s1["velocities"]) == set(s2["velocities"])
    for name in s1["velocities"]:
        for k in s1["velocities"][name]:
            a, b = s1["velocities"][name][k], s2["velocities"][name][k]
            assert a.dtype == b.dtype, (name, k)
            if exact_arrays:
                np.testing.assert_array_equal(a, b, err_msg=f"{name}.{k}")
            else:
                np.testing.assert_allclose(
                    np.asarray(a, np.float32), np.asarray(b, np.float32),
                    rtol=1e-4, atol=1e-6, err_msg=f"{name}.{k}")
    for f in ("epoch_number", "samples_served", "last_minibatch"):
        assert s1["loader"][f] == s2["loader"][f], f
    np.testing.assert_array_equal(s1["loader"]["shuffled_indices"],
                                  s2["loader"]["shuffled_indices"])
    assert s1["epoch"] == s2["epoch"]
    np.testing.assert_allclose(s1["metric"], s2["metric"], rtol=1e-6)
    assert set(s1["prng"]) == set(s2["prng"])
    for name in s1["prng"]:
        assert repr(s1["prng"][name]) == repr(s2["prng"][name]), name


def test_async_snapshot_equals_sync(tmp_path):
    """Segmented path: the async (background-thread) snapshot is the SAME
    checkpoint the synchronous collect()+save() produces — identical
    weights, velocities (same dtype), loader shuffle state and prng
    streams — and training results do not depend on the setting."""
    root.common.dirs.snapshots = str(tmp_path / "async")
    la, ta = _run_fused(fresh_mnist(max_epochs=3))
    wf_a = ta.workflow
    assert wf_a.snapshotter.async_saves_written > 0
    snap_a = _load_snap(wf_a.snapshotter.destination)

    root.common.engine.async_snapshot = False
    try:
        root.common.dirs.snapshots = str(tmp_path / "sync")
        ls, ts = _run_fused(fresh_mnist(max_epochs=3))
        wf_s = ts.workflow
        assert wf_s.snapshotter.async_saves_written == 0
        snap_s = _load_snap(wf_s.snapshotter.destination)
    finally:
        root.common.engine.async_snapshot = True

    np.testing.assert_allclose(la, ls, rtol=0, atol=0)   # same trajectory
    _assert_snaps_equal(snap_a, snap_s, exact_arrays=True)


def test_deep_snapshot_equals_segmented(tmp_path):
    """Deep-pipeline path (r4 weak #3 closed): with an ACTIVE snapshotter
    the run stays in deep mode, writes its checkpoints at flush
    boundaries, and the checkpoint content matches the segmented path's —
    including the flushed epoch's OWN loader/prng state, not the
    pipelined-ahead live state."""
    root.common.dirs.snapshots = str(tmp_path / "seg")
    l1, t1 = _run_fused(fresh_mnist(max_epochs=3), depth=1)
    snap_seg = _load_snap(t1.workflow.snapshotter.destination)

    root.common.dirs.snapshots = str(tmp_path / "deep")
    l3, t3 = _run_fused(fresh_mnist(max_epochs=3), depth=3)
    wf3 = t3.workflow
    assert wf3.snapshotter.async_saves_written > 0
    snap_deep = _load_snap(wf3.snapshotter.destination)

    np.testing.assert_allclose(l1, l3, rtol=1e-5)
    # trajectories are float-close (deep reorders reductions slightly);
    # loader/prng/decision bookkeeping must be EXACT
    _assert_snaps_equal(snap_seg, snap_deep, exact_arrays=False)


def test_deep_async_snapshot_resume_parity(tmp_path):
    """The deep path's async checkpoint is a REAL resume point (the
    test_fused_snapshot_restore_continue contract, now for the deep+async
    configuration): continuing from it lands on the same trajectory
    whichever engine continues — fused (segmented OR deep) or the unit
    graph.  (Resume-from-stop is NOT compared against an uninterrupted
    longer run: a max_epochs stop drops the final tail update by Decision
    semantics, so the trajectories legitimately differ there.)"""
    from znicz_tpu import snapshotter as snap_mod
    from znicz_tpu.core import prng
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    root.common.dirs.snapshots = str(tmp_path)
    l_run, t_run = _run_fused(fresh_mnist(max_epochs=2), depth=2)
    wf1 = t_run.workflow
    assert wf1.snapshotter.async_saves_written > 0
    snap = _load_snap(wf1.snapshotter.destination)
    assert snap["epoch"] == 1                      # 0-based second epoch

    def continue_run(engine, depth=1):
        prng.reset(1013)
        root.mnist.decision.max_epochs = 4
        losses = []
        wf2 = mnist.MnistWorkflow()
        wf2.decision.on_epoch_end.append(
            lambda d: losses.append(d.epoch_metrics[2]["loss"]))
        wf2.initialize(device=None)
        snap_mod.restore(wf2, snap)
        if engine == "fused":
            tr = FusedTrainer(wf2)
            tr.pipeline_depth = depth
            tr.run()
        else:
            wf2.run()
        assert bool(wf2.decision.complete)
        return losses, {f.name: np.array(f.weights.map_read())
                        for f in wf2.forwards}

    lf, wf_f = continue_run("fused", depth=1)
    ld, wf_d = continue_run("fused", depth=3)
    lu, wf_u = continue_run("unit")
    assert len(lf) == 2 and len(ld) == 2 and len(lu) == 2
    np.testing.assert_allclose(lf, ld, rtol=1e-5)
    np.testing.assert_allclose(lf, lu, rtol=1e-4)
    for name in wf_u:
        np.testing.assert_allclose(wf_u[name], wf_f[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)
        np.testing.assert_allclose(wf_f[name], wf_d[name], rtol=1e-4,
                                   atol=1e-6, err_msg=name)


def test_async_snapshot_coalesces_but_final_is_durable(tmp_path):
    """The writer coalesces superseded queued jobs (bounded backlog on
    slow links) but the LAST due snapshot of the run is always written
    before run() returns."""
    root.common.dirs.snapshots = str(tmp_path)
    wf = fresh_mnist(max_epochs=4)
    losses, tr = _run_fused(wf)
    snap = wf.snapshotter
    assert snap.async_saves_written > 0
    dest = snap.destination
    assert dest is not None and os.path.exists(dest)
    loaded = _load_snap(dest)
    # the checkpoint is internally consistent: restoring it reproduces
    # the recorded best metric
    assert np.isfinite(loaded["metric"])


def test_cross_dtype_checkpoint_restore(tmp_path):
    """ADVICE r4: a checkpoint stores velocities in the THEN-configured
    state_dtype; restoring under a different configuration explicitly
    casts to the live dtype — both for host-format restore() and for the
    sharded-orbax restore_sharded() template path — instead of erroring
    or silently changing the run's accumulator precision."""
    from znicz_tpu import snapshotter as snap_mod
    from znicz_tpu.core import prng
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    root.common.dirs.snapshots = str(tmp_path)

    # save under bf16 optimizer state
    root.common.engine.state_dtype = "bfloat16"
    try:
        _run_fused(fresh_mnist(max_epochs=2))
    finally:
        root.common.engine.state_dtype = "float32"
    wf_src = None  # the snapshot file is what we need
    pickle_path = str(tmp_path / "mnist_best.pickle.gz")
    assert os.path.exists(pickle_path)

    # restore under f32 state: velocities arrive CAST to f32
    prng.reset(1013)
    root.mnist.decision.max_epochs = 4
    wf2 = mnist.MnistWorkflow()
    wf2.initialize(device=None)
    snap = snap_mod.Snapshotter.load(pickle_path)
    vel_leaf = next(iter(next(iter(snap["velocities"].values())).values()))
    assert str(vel_leaf.dtype) == "bfloat16"       # stored as configured
    snap_mod.restore(wf2, snap)
    for gd in wf2.gds:
        for k, a in gd._velocities.items():
            assert str(a.mem.dtype) == "float32", (gd.name, k)
    tr2 = FusedTrainer(wf2)
    tr2.run()                                      # continues cleanly
    assert bool(wf2.decision.complete)

    # sharded-orbax direction: save f32, restore under bf16 state
    root.mnist.decision.max_epochs = 2
    prng.reset(1013)
    wf3 = fresh_mnist(max_epochs=2)
    wf3.snapshotter.format = "orbax"
    wf3.snapshotter.sharded = True
    tr3 = FusedTrainer(wf3)
    tr3.run()
    orbax_path = wf3.snapshotter.destination
    assert orbax_path and orbax_path.endswith(".orbax")

    root.common.engine.state_dtype = "bfloat16"
    try:
        prng.reset(1013)
        root.mnist.decision.max_epochs = 4
        wf4 = mnist.MnistWorkflow()
        wf4.initialize(device=None)
        tr4 = FusedTrainer(wf4)
        tr4.restore_sharded(orbax_path)
        for gd in wf4.gds:
            for k, a in gd._velocities.items():
                assert str(a.devmem.dtype) == "bfloat16", (gd.name, k)
        tr4.run()
        assert bool(wf4.decision.complete)
    finally:
        root.common.engine.state_dtype = "float32"
