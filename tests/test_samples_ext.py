"""Extended L10 sample family: Kanji (many-class) and VideoAE (frame
autoencoder) — SURVEY §1 L10 sample list."""

import numpy as np

from znicz_tpu.core.config import root


def test_kanji_trains_many_class(tmp_path):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import kanji

    prng.reset(1013)
    root.kanji.loader.n_train = 1024
    root.kanji.loader.n_valid = 256
    root.kanji.loader.n_classes = 32
    root.kanji.loader.minibatch_size = 128
    root.kanji.decision.max_epochs = 6
    root.common.dirs.snapshots = str(tmp_path)
    wf = kanji.run()
    dec = wf.decision
    assert bool(dec.complete)
    valid = dec.epoch_metrics[1]
    # 32 classes -> chance err ~96.9%; strokes are learnable
    assert valid is not None and valid["err_pct"] < 40.0, valid
    assert wf.forwards[-1].output.shape[-1] == 32


def test_video_ae_learns_frame_manifold(tmp_path):
    from znicz_tpu.core import prng
    from znicz_tpu.loader.base import TRAIN, VALID
    from znicz_tpu.samples import video_ae

    prng.reset(1013)
    root.video_ae.loader.n_train = 800
    root.video_ae.loader.n_valid = 200
    root.video_ae.loader.minibatch_size = 100
    root.video_ae.decision.max_epochs = 20
    root.common.dirs.snapshots = str(tmp_path)
    wf = video_ae.run()
    dec = wf.decision
    assert bool(dec.complete)
    final = dec.epoch_metrics[TRAIN]["loss"]
    # the AE reconstructs far better than predicting the mean frame:
    # compare against the variance-based MSE of the training frames
    data = np.asarray(wf.loader.original_data.mem)
    per_sample = data.reshape(len(data), -1)
    base = 0.5 * float(
        np.mean(np.sum(np.square(per_sample - per_sample.mean(0)), axis=1)))
    assert final < 0.5 * base, (final, base)
    assert dec.epoch_metrics[VALID]["loss"] < base


def test_samples_fused_engine_smoke(tmp_path):
    """The new samples also run under the fused fast path (--fused)."""
    from znicz_tpu.core import prng
    from znicz_tpu.samples import kanji

    prng.reset(1013)
    root.kanji.loader.n_train = 256
    root.kanji.loader.n_valid = 128
    root.kanji.loader.n_classes = 16
    root.kanji.loader.minibatch_size = 128
    root.kanji.decision.max_epochs = 2
    root.common.dirs.snapshots = str(tmp_path)
    root.common.engine.fused = True
    try:
        wf = kanji.run()
    finally:
        root.common.engine.fused = False
    assert bool(wf.decision.complete)
    assert wf.fused_stats["train_steps"] > 0
