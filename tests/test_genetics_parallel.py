"""Parallel genetics: a generation's individuals evaluated as concurrent
launcher subprocesses (SURVEY.md §2.1 Genetics "multiprocess evaluation"),
with results identical to the sequential path."""

import os
import sys
import textwrap

from znicz_tpu.core import prng
from znicz_tpu.core.config import root

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fake_workflow(tmp_path) -> str:
    """A launcher-compatible workflow whose fitness is a deterministic bowl
    over the tuned leaves — exercises the full subprocess machinery
    (override passing, --fitness parsing) without device work."""
    path = tmp_path / "bowl_wf.py"
    path.write_text(textwrap.dedent("""\
        from znicz_tpu.core.config import root


        class _Obj:
            pass


        def run(**kwargs):
            wf = _Obj()
            wf.decision = _Obj()
            x = float(root.ga_bowl.x)
            y = float(root.ga_bowl.y)
            wf.decision.best_metric = (x - 0.3) ** 2 + (y + 0.2) ** 2
            return wf
    """))
    return str(path)


def _optimize(tmp_path, workers: int):
    from znicz_tpu.genetics import (GeneticsOptimizer, SubprocessEvaluator,
                                    Tune)

    prng.reset(1013)
    cfg = root.ga_bowl
    cfg.x = Tune(0.9, -1.0, 1.0)
    cfg.y = Tune(0.8, -1.0, 1.0)
    evaluator = SubprocessEvaluator(
        workflow=_fake_workflow(tmp_path), prefix="root.ga_bowl",
        timeout=120.0)
    opt = GeneticsOptimizer(
        config_root=cfg, generations=2, population=3, elite=1,
        workers=workers, subprocess_evaluator=evaluator)
    best, fitness = opt.run()
    return best, fitness, opt


def test_parallel_matches_sequential(tmp_path):
    bp, fp, opt_p = _optimize(tmp_path, workers=2)
    bs, fs, opt_s = _optimize(tmp_path, workers=1)
    assert opt_p.max_parallel >= 2          # genuinely ran concurrently
    assert opt_s.max_parallel == 1
    assert fp == fs
    assert list(bp) == list(bs)
    assert opt_p.history == opt_s.history
    assert fp < (0.9 - 0.3) ** 2 + (0.8 + 0.2) ** 2   # beats the default


def test_launcher_fitness_flag(tmp_path):
    """--fitness prints a parseable JSON line for a real sample workflow."""
    import json
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-m", "znicz_tpu", "wine",
         "root.wine.decision.max_epochs=2", "--fitness"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.strip().splitlines()
            if "genetics_fitness" in ln][-1]
    assert json.loads(line)["genetics_fitness"] >= 0.0


def test_launcher_fitness_nonfinite_is_no_fitness(tmp_path):
    """A run whose best_metric never left inf must exit 3 with no
    genetics_fitness line (json 'Infinity' is not RFC JSON)."""
    import subprocess

    # 0 epochs: decision never observes a validation metric
    proc = subprocess.run(
        [sys.executable, "-m", "znicz_tpu", "wine",
         "root.wine.decision.max_epochs=0", "--fitness"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    if proc.returncode == 0:
        # some samples still record a finite metric after epoch 0; the
        # contract under test is only: never print non-finite fitness
        assert "Infinity" not in proc.stdout
    else:
        assert proc.returncode == 3, proc.stderr[-2000:]
        assert "genetics_fitness" not in proc.stdout
