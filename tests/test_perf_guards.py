"""Throughput regression guards that run on the CPU backend (VERDICT r4
items 7 and 8): the staging MACHINERY must be compute-bound where the
link is a memcpy, and always-on confusion must stay effectively free.
Timing-based, so every assertion uses median-of-windows and a margin far
wider than the effect a real regression would produce."""

import time

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.config import root


def _warm_rate(budget):
    from tests.test_streaming import _StreamingMnistLoader
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 2048
    root.mnist.loader.n_valid = 256
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 256
    root.mnist.decision.max_epochs = 4
    # wide enough that compute dominates: the guard measures the staging
    # MACHINERY's share at a realistic compute:transfer ratio (AlexNet's
    # is far higher still), not a degenerate all-overhead microbenchmark
    root.mnist.layers = [512, 10]
    _StreamingMnistLoader.u8 = True
    _StreamingMnistLoader.budget = budget
    orig = mnist.MnistLoader
    mnist.MnistLoader = _StreamingMnistLoader
    try:
        wf = mnist.MnistWorkflow()
    finally:
        mnist.MnistLoader = orig
        root.mnist.layers = [100, 10]
    wf.initialize(device=None)
    trainer = FusedTrainer(wf)
    trainer.run()
    assert bool(wf.decision.complete)
    return trainer.stats["warm_img_per_sec"], wf


def test_staging_machinery_compute_bound_on_cpu():
    """VERDICT r4 item 7: where H2D is a memcpy (the CPU backend), the
    staging machinery itself — host row gather, per-segment device_put,
    the staged-direct scan — must not cost more than a sliver of the
    step rate: staged throughput >= 80% of u8-resident throughput (the
    true overhead measures ~<10%; the margin absorbs CI timer noise).
    The bit-parity half of the contract is tests/test_streaming.py."""
    _warm_rate(budget=1 << 30)                    # compile warm
    _warm_rate(budget=0)
    resident_rate = max(_warm_rate(budget=1 << 30)[0] for _ in range(2))
    staged_rate = 0.0
    for _ in range(2):
        r, wf = _warm_rate(budget=0)
        assert not wf.loader.device_resident      # really staged
        staged_rate = max(staged_rate, r)
    assert staged_rate >= 0.8 * resident_rate, \
        (staged_rate, resident_rate)


def test_confusion_always_on_costs_under_margin():
    """VERDICT r4 item 8: the fused path's always-on confusion is a
    device-side scan-carry accumulator with a once-per-epoch transfer.
    CALIBRATION of this CPU guard: on a 1-core CPU backend the wide
    (1000,1000) accumulator adds a real 15-30% to a small-MLP step —
    unlike on TPU, where the r4/r5 headline carries it at per-mille cost
    (the bench's job to watch).  What this guard exists to catch is the
    REGRESSION CLASS: re-introducing a per-step host transfer of the
    (C,C) matrix, which costs MULTIPLES (the r3 measurement: 28 MB per
    segment).  So the assertion is a 2x band, robustly above the
    platform-noise floor and far below any real regression."""
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    n_classes = 1000   # wide head: the (C,C) accumulator is 1M int32s

    def run_once(confusion_on):
        prng.reset(1013)
        root.mnist.loader.n_train = 1024
        root.mnist.loader.n_valid = 128
        root.mnist.loader.n_test = 0
        root.mnist.loader.minibatch_size = 128
        root.mnist.decision.max_epochs = 3
        # hidden width sized so compute dominates the way it does on any
        # real model: the guard asserts the accumulator's RELATIVE cost
        # (a 1000^2 int32 add per step is ~fixed work; against a
        # 100-wide MLP on CPU it is ~30% — against this one, percents,
        # and against the AlexNet bench head, per-mille)
        root.mnist.layers = [512, n_classes]
        try:
            wf = mnist.MnistWorkflow()
        finally:
            root.mnist.layers = [100, 10]
        # the sample draws 10-class labels; the head is just WIDER
        wf.initialize(device=None)
        if not confusion_on:
            wf.evaluator.compute_confusion = False
            wf.evaluator.confusion_explicit = True
        trainer = FusedTrainer(wf)
        trainer.run()
        return trainer.stats["warm_img_per_sec"], trainer

    # compile + cache warm for both variants, then measured runs.
    # BEST-of-3 warm rates: suite-context load spikes only ever slow a
    # run down, so the max approximates each variant's clean capability —
    # exactly the question (a regression re-introducing a per-step
    # transfer suppresses the best case too, by multiples).
    run_once(True)
    run_once(False)
    on = max(run_once(True)[0] for _ in range(3))
    off = max(run_once(False)[0] for _ in range(3))
    # sanity: the on-variant really collected a wide confusion
    _, tr = run_once(True)
    assert tr.compute_confusion and tr._n_confusion() == n_classes
    assert on >= off * 0.5, (on, off)


def test_anchor_bands_enforced():
    """VERDICT r4 item 6: the seeded sample anchors are tolerance BANDS a
    math change cannot silently cross.  Unit half: check_anchor flags
    out-of-band finals (e.g. the r3 pow-LRN CIFAR error, 41.25%, is
    outside the r4 rsqrt band 44.0 +/- 1.5 — re-running the old math
    FAILS --samples until BASELINE.md justifies a re-center).  E2e half:
    the cheapest real anchor (config 0, MNIST) still lands in band."""
    import bench

    # the unit half
    assert bench.check_anchor(1, {"final_train_loss": 0.9501,
                                  "valid_err_pct": 44.0}) == []
    bad = bench.check_anchor(1, {"final_train_loss": 0.9499,
                                 "valid_err_pct": 41.25})
    assert [f["metric"] for f in bad] == ["valid_err_pct"]

    # the e2e half: run BASELINE config 0 exactly like --samples does
    # (restore the sample's defaults first — sibling tests shrink them)
    root.mnist.loader.n_train = 4000
    root.mnist.loader.n_valid = 800
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 5
    root.mnist.layers = [100, 10]
    prng.reset(1013)
    from znicz_tpu.samples import mnist

    wf = mnist.run()
    vals = bench._gd_finals(wf.decision)
    assert bench.check_anchor(0, vals) == [], vals


def test_async_snapshot_does_not_stall_training_cpu():
    """VERDICT r4 item 4 gate: every-epoch snapshots (interval=1) must
    bill their cost to the background writer, not the training thread.

    RESTRUCTURED (VERDICT r5 next-item 6; the old form compared two
    wall-clock throughputs, gated vs active, and flaked in-suite
    because this box's cgroup CPU share swings 4x minute-to-minute —
    any band wide enough to absorb that swing was too wide to mean
    anything).  The property is WHERE the save cost lands, so test it
    structurally: inject a deliberate DELAY into the disk-write path
    and assert each ``save_async`` call made by the training loop
    returns in a small fraction of it.  A regression of the guarded
    class — the per-epoch writeback+pickle made synchronous again —
    bills >= DELAY to every call and fails by multiples, while host
    load cannot fake a 0.6 s stall inside a lock-append-notify.  The
    writes still really happen (async_saves_written through the slowed
    writer), so the worker handoff is exercised end to end, and the
    run's decision loop overlaps compute with the artificially slow
    writer exactly as on the TPU host, where the device->host pull is
    ~60 s of shared-link occupancy (BASELINE.md carries that measured
    analysis)."""
    import tempfile

    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 512
    root.mnist.loader.n_valid = 128
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 128
    root.mnist.decision.max_epochs = 4
    root.mnist.snapshotter.interval = 1
    try:
        wf = mnist.MnistWorkflow()
    finally:
        root.mnist.snapshotter.interval = 0
    wf.initialize(device=None)
    snap = wf.snapshotter
    snap.directory = tempfile.mkdtemp(prefix="snapstall_")

    DELAY = 0.6
    real_write = snap._write_host_format

    def slow_write(path, s):
        time.sleep(DELAY)               # stands in for the TPU host's
        real_write(path, s)             # link-bound pull+write

    snap._write_host_format = slow_write

    calls = []
    real_save_async = snap.save_async

    def timed_save_async(s, tags):
        t0 = time.perf_counter()
        real_save_async(s, tags)
        calls.append(time.perf_counter() - t0)

    snap.save_async = timed_save_async

    trainer = FusedTrainer(wf)
    trainer.run()
    # the async path was really taken, and every queued save was
    # durably written THROUGH the slowed writer (run() drains the queue)
    assert calls, "async snapshot path not taken"
    assert snap.async_saves_written >= 3, snap.async_saves_written
    # the structural gate: handing a snapshot to the writer is a
    # lock-append-notify, orders of magnitude under DELAY; synchronous
    # saving would bill >= DELAY per call
    assert max(calls) < 0.4 * DELAY, (calls, DELAY)


def test_bf16_master_weights_variant_trains():
    """The opt-in bf16-MASTER-weights traffic lever
    (root.common.engine.master_dtype — a labeled bench variant, never
    the headline/anchors): params are stored bf16, update math stays
    f32, and training still converges to the f32 run's neighborhood."""
    from znicz_tpu.parallel.fused import FusedTrainer

    from tests.test_fused import fresh_mnist, run_fused

    l32, _ = run_fused(fresh_mnist(max_epochs=3))
    root.common.engine.master_dtype = "bfloat16"
    try:
        wf = fresh_mnist(max_epochs=3)
        losses = []
        wf.decision.on_epoch_end.append(
            lambda d: losses.append(d.epoch_metrics[2]["loss"]))
        tr = FusedTrainer(wf)
        assert tr._master_dtype == "bfloat16"
        tr.run()
        w = wf.forwards[0].weights.map_read()
        assert str(w.dtype) == "bfloat16"       # stored dtype really bf16
    finally:
        root.common.engine.master_dtype = "float32"
    # loose band: bf16 weight rounding shifts the trajectory, it must
    # not break it
    assert losses[-1] < 1.5 * l32[-1] + 0.05, (losses, l32)

    # and the config validates
    root.common.engine.master_dtype = "float16"
    try:
        import pytest as _pytest

        with _pytest.raises(ValueError, match="master_dtype"):
            FusedTrainer(fresh_mnist(max_epochs=1))
    finally:
        root.common.engine.master_dtype = "float32"
