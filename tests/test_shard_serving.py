"""Pod-scale sharded serving (ISSUE 13): the mesh-native ModelRunner.

Covers: dp-snapped bucket ladders + readable non-divisible refusals,
per-device shard shapes (rows/dp on every data-axis device, staged AND
computed), the 0-ULP batch-independence contract WITHIN a mesh, the
cross-mesh parity band (1x1 vs 4x1 vs 2x2 — reduction tiling is
layout-dependent, so cross-LAYOUT parity is numerical, exactly the
reason PR 4 pinned its 0-ULP contract per bucket executable),
zero-recompiles on the sharded path, swap/rollback placement +
generation stamps, the stage copy-skip counter, capacity-weighted
balancer dispatch, and the e2e sharded service.  Soaks ride behind the
``slow`` marker.

Runs on the 8 virtual CPU devices conftest provisions (virtdev.py)."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.config import root

#: cross-layout parity band, relative to max|y| per rung (see
#: bench.py SHARD_PARITY_REL: measured ~1e-6 reduction-order noise on
#: this stack; a real math divergence lands orders of magnitude higher)
PARITY_REL = 1e-5


def _tiny_mnist_wf(n_train=120, layers=None):
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = n_train
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    if layers is not None:
        root.mnist.layers = list(layers)
    try:
        wf = mnist.MnistWorkflow()
    finally:
        root.mnist.layers = [100, 10]
    wf.initialize(device=None)
    return wf


def _mesh(dp, mp=1):
    from znicz_tpu.parallel.mesh import make_mesh

    return make_mesh((dp, mp), ("data", "model"))


def _pad(x, b):
    out = np.zeros((b,) + x.shape[1:], np.float32)
    out[:len(x)] = x
    return out


@pytest.fixture
def serving_mesh():
    """Set ``root.common.serving.mesh.*`` for a test and restore the
    (absent -> 1x1) default after — the global config tree must not
    leak a mesh into the rest of the suite."""
    def set_mesh(dp, mp=1):
        root.common.serving.mesh.data = int(dp)
        root.common.serving.mesh.model = int(mp)
    yield set_mesh
    delattr(root.common.serving, "mesh")


# -- ladder snapping + readable refusals --------------------------------------


def test_ladder_dp_snapping_and_mesh_refusals():
    from znicz_tpu.parallel.mesh import make_mesh
    from znicz_tpu.serving import BucketLadder

    # default rungs snap UP to multiples of dp (then dedupe)
    assert BucketLadder(32, dp=4).rungs == [4, 8, 16, 32]
    assert BucketLadder(8, dp=4).rungs == [4, 8]
    assert BucketLadder(24, dp=4).rungs == [4, 8, 16, 24]
    assert BucketLadder(32).rungs == [1, 2, 4, 8, 16, 32]  # dp=1 intact
    # explicit rungs that cannot split are refused readably
    with pytest.raises(ValueError, match="divide across"):
        BucketLadder(8, rungs=[2, 8], dp=4)
    # a max_batch that cannot split is refused at construction
    with pytest.raises(ValueError, match="multiple of dp"):
        BucketLadder(30, dp=4)
    # make_mesh refuses an over-sized mesh with the virtdev recipe in
    # the message, not a raw reshape failure (ISSUE 13 satellite)
    with pytest.raises(ValueError) as exc:
        make_mesh((16, 2), ("data", "model"))
    msg = str(exc.value)
    assert "provision_cpu_devices" in msg and "XLA_FLAGS" in msg


# -- the sharded runner contract ----------------------------------------------


def test_sharded_runner_shapes_parity_recompiles(serving_mesh):
    """One 1024-wide workflow, three layouts: shard shapes exact, 0-ULP
    batch independence within each mesh, cross-mesh parity in band,
    zero recompiles over a mixed stream, column-sharded FC weights on
    the model axis, and the e2e service under the mesh config."""
    from jax.sharding import PartitionSpec as P

    from znicz_tpu.serving import (BucketLadder, InferenceClient,
                                   InferenceServer, ModelRunner)

    wf = _tiny_mnist_wf(layers=[1024, 10])   # >= tp_threshold: the
    # model axis engages on the first FC layer
    rng = np.random.default_rng(7)
    x8 = rng.normal(0, 1, (8, 784)).astype(np.float32)
    ref = ModelRunner(wf)
    ref_y = {r: ref.infer(x8[:r]) for r in (2, 4, 8)}

    for dp, mp in ((4, 1), (2, 2)):
        runner = ModelRunner(wf, mesh=_mesh(dp, mp))
        assert runner.data_parallel == dp
        assert runner.device_count == dp * mp
        assert runner.mesh_shape == {"data": dp, "model": mp}
        ladder = BucketLadder(8, dp=dp)
        warm = runner.warmup(ladder)
        assert warm == len(ladder.rungs)
        if mp > 1:
            # the wide FC weight is column-sharded over ``model``
            specs = [leaf.sharding.spec
                     for layer in runner.params.values()
                     for leaf in layer.values()
                     if leaf.shape and leaf.shape[0] == 1024]
            assert P("model", None) in specs
        for rung in ladder:
            staged = runner.stage(x8[:rung])
            shards = [s.data.shape for s in staged.addressable_shards]
            assert len(shards) == dp * mp
            assert all(s[0] == rung // dp for s in shards)
            y_dev, gen = runner.infer_staged(staged)
            assert gen == 1
            assert all(s.data.shape[0] == rung // dp
                       for s in y_dev.addressable_shards)
            # cross-mesh parity: numerical band, per rung
            y = np.asarray(y_dev)[:rung]
            rel = np.max(np.abs(y - ref_y[rung])) \
                / max(np.max(np.abs(ref_y[rung])), 1e-30)
            assert rel <= PARITY_REL, (dp, mp, rung, rel)
        # 0-ULP batch independence WITHIN this mesh: coalescing,
        # offset and pad content cannot perturb a request's rows
        alone = [runner.infer(_pad(p, 8))[:len(p)]
                 for p in (x8[:5], x8[5:])]
        together = runner.infer(x8)
        assert np.array_equal(together[:5], alone[0])
        assert np.array_equal(together[5:], alone[1])
        garbage = _pad(x8[:5], 8)
        garbage[5:] = 1e9
        assert np.array_equal(runner.infer(garbage)[:5], alone[0])
        # mixed-size stream: every size pads to a rung, zero recompiles
        c0, j0 = runner.compiles, runner.jit_cache_size()
        for n in (1, 3, 8, 5, 2, 7, 4, 6):
            runner.infer(_pad(x8[:n], ladder.bucket_for(n)))
        assert runner.compiles == c0
        if j0 is not None:
            assert runner.jit_cache_size() == j0

    # e2e: the service built under the mesh CONFIG snaps its ladder,
    # serves mixed sizes bit-exactly vs its own runner, recompiles
    # nothing, and heartbeats its capacity
    serving_mesh(4, 1)
    srv = InferenceServer(wf, max_batch=8, max_delay_ms=2.0,
                          queue_bound=64).start()
    cli = InferenceClient(srv.endpoint, timeout=30)
    try:
        assert srv.runner.data_parallel == 4
        assert srv.batcher.ladder.rungs == [4, 8]
        compiles_warm = srv.runner.compiles
        for n in (1, 3, 8, 5):
            x = x8[:n]
            y = cli.infer(x)
            ref_b = srv.runner.infer(
                srv.runner.pad(x, srv.batcher.ladder.bucket_for(n)))[:n]
            assert np.array_equal(y, ref_b)
        assert srv.runner.compiles == compiles_warm
        hb = srv.heartbeat_payload()
        assert hb["device_count"] == 4
        assert hb["mesh"] == {"data": 4, "model": 1}
        assert srv.stats()["model"]["mesh"] == {"data": 4, "model": 1}
    finally:
        cli.close()
        srv.stop()


def test_sharded_swap_rollback_placement_and_stage_copies(
        tmp_path, serving_mesh):
    from jax.sharding import NamedSharding

    from znicz_tpu.serving import BucketLadder, ModelRunner

    wf = _tiny_mnist_wf()
    wf.snapshotter.directory = str(tmp_path)
    path_a = wf.snapshotter.save("gen1")
    runner = ModelRunner(wf, mesh=_mesh(4))
    ladder = BucketLadder(8, dp=4)
    runner.warmup(ladder)
    rng = np.random.default_rng(23)
    x = rng.normal(0, 1, (8, 784)).astype(np.float32)
    y1 = runner.infer(x)

    # perturb + save gen2 (bit-distinguishable outputs)
    for f in wf.forwards:
        for k, a in f.params().items():
            a.mem = np.asarray(a.map_read()) * np.float32(1.25) \
                + np.float32(0.01)
    path_b = wf.snapshotter.save("gen2")

    compiles = runner.compiles
    runner.swap(path_b, ladder)
    assert runner.compiles == compiles    # warm = sharded cache hits
    assert runner.generation == 2
    # the NEW tree landed in mesh placement: every leaf carries a
    # NamedSharding on THIS runner's mesh (replicated or model-sharded)
    for layer in runner.params.values():
        for leaf in layer.values():
            assert isinstance(leaf.sharding, NamedSharding)
            assert leaf.sharding.mesh == runner.mesh
    y2 = runner.infer(x)
    assert not np.array_equal(y1, y2)     # generations distinguishable
    # results still split rows/dp after the swap
    y_dev, gen = runner.infer_staged(runner.stage(x))
    assert gen == 2
    assert all(s.data.shape[0] == 2 for s in y_dev.addressable_shards)

    gen = runner.rollback()
    assert gen == 1 and runner.generation == 1
    assert runner.snapshot_path == path_a or runner.snapshot_path == ""
    assert np.array_equal(runner.infer(x), y1)    # bit-exact restore
    for layer in runner.params.values():
        for leaf in layer.values():
            assert leaf.sharding.mesh == runner.mesh

    # stage copy-skip satellite: a contiguous right-dtype batch stages
    # with NO host copy; strided or wrong-dtype input pays one, counted
    before = runner.stage_copies
    runner.stage(np.ascontiguousarray(x, runner.dtype))
    assert runner.stage_copies == before
    runner.stage(x[::2])                  # strided view: must copy
    assert runner.stage_copies == before + 1
    runner.stage(x.astype(np.float64))    # wrong dtype: must copy
    assert runner.stage_copies == before + 2
    # non-divisible batches are refused readably, not an XLA error
    with pytest.raises(ValueError, match="does not divide"):
        runner.stage(np.zeros((6, 784), np.float32))


# -- capacity-weighted fleet dispatch (ISSUE 13 satellite) --------------------


def test_balancer_capacity_weighted_dispatch_and_mesh_column():
    from znicz_tpu.serving import ReplicaBalancer
    from znicz_tpu.web_status import WebStatus

    bal = ReplicaBalancer(bind="tcp://127.0.0.1:*")

    def member(endpoint, queue_depth, device_count, mesh=None):
        return {"endpoint": endpoint, "last_seen": time.perf_counter(),
                "ready": True, "gen": 1, "queue_depth": queue_depth,
                "swapping": False, "draining": False,
                "snapshot_path": "", "device_count": device_count,
                "mesh": mesh, "p99_ms_by_bucket": {}}

    with bal._lock:
        # same raw queue depth, 8x the capacity: the pod slice must
        # rank FIRST (load normalized by device count), where the old
        # raw-sum ranking would have tied and round-robined
        bal._members["pod8"] = member(
            "tcp://127.0.0.1:7001", 4, 8, {"data": 4, "model": 2})
        bal._members["chip1"] = member("tcp://127.0.0.1:7002", 4, 1)
        order = bal._candidates()
    assert order[0] == "pod8"
    with bal._lock:
        # capacity-normalized, not absolute: 16 rows on 8 chips (2 per
        # chip) still beats 3 rows on one chip
        bal._members["pod8"]["queue_depth"] = 16
        bal._members["chip1"]["queue_depth"] = 3
        order = bal._candidates()
    assert order[0] == "pod8"
    # the fleet panel shows the mesh column
    stats = bal.stats()
    by_id = {m["replica_id"]: m for m in stats["replicas"]}
    assert by_id["pod8"]["mesh"] == {"data": 4, "model": 2}
    assert by_id["chip1"]["device_count"] == 1
    status = WebStatus(port=0).start()
    try:
        status.register_balancer(bal)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/") as r:
            page = r.read().decode()
        assert "<th>mesh</th>" in page and "4x2 (8d)" in page
    finally:
        status.stop()
    # a legacy heartbeat without device_count defaults to 1 (no crash)
    with bal._lock:
        del bal._members["pod8"]["device_count"]
        assert bal._candidates()


# -- soak (slow) --------------------------------------------------------------


@pytest.mark.slow
def test_sharded_service_rollover_soak(tmp_path, serving_mesh):
    """Sustained mixed-size load on a {data:4} service with a swap and
    a rollback mid-stream: every reply bit-matches its stamped
    generation's per-rung oracle, nothing is lost, and the mixed
    stream + two rollovers cause zero recompiles."""
    from znicz_tpu.serving import InferenceClient, InferenceServer

    wf = _tiny_mnist_wf()
    wf.snapshotter.directory = str(tmp_path)
    serving_mesh(4, 1)
    srv = InferenceServer(wf, max_batch=8, max_delay_ms=1.0,
                          queue_bound=64).start()
    rng = np.random.default_rng(31)
    x1 = rng.normal(0, 1, (1, 784)).astype(np.float32)
    refs = {1: {b: srv.runner.infer(srv.runner.pad(x1, b))[:1]
                for b in srv.batcher.ladder.rungs}}
    for f in wf.forwards:
        for k, a in f.params().items():
            a.mem = np.asarray(a.map_read()) * np.float32(1.25) \
                + np.float32(0.01)
    path_b = wf.snapshotter.save("gen2")
    compiles_warm = srv.runner.compiles
    cli = InferenceClient(srv.endpoint, timeout=60)
    results = []
    errs = []
    stop = threading.Event()

    def load():
        try:
            while not stop.is_set():
                rep = cli.result(cli.submit(x1))
                results.append((rep["gen"], rep["y"]))
        except Exception as exc:          # pragma: no cover - failure
            errs.append(exc)

    t = threading.Thread(target=load, daemon=True)
    t.start()
    try:
        time.sleep(0.5)
        srv.swap_async(path_b).join(timeout=60)
        assert srv.runner.generation == 2
        refs[2] = {b: srv.runner.infer(srv.runner.pad(x1, b))[:1]
                   for b in srv.batcher.ladder.rungs}
        time.sleep(0.5)
        srv.runner.rollback()
        assert srv.runner.generation == 1
        time.sleep(0.5)
    finally:
        stop.set()
        t.join(timeout=30)
        cli.close()
        srv.stop()
    assert not errs
    gens = {g for g, _ in results}
    assert gens == {1, 2}                 # both generations served
    for g, y in results:
        assert any(np.array_equal(y, ref)
                   for ref in refs[g].values()), g
    # the oracle probes above ran through the same rung executables:
    # two rollovers + the stream added no compiles
    assert srv.runner.compiles == compiles_warm
