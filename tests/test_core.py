"""Core-engine tests: config, Bool gates, unit linking, workflow scheduling,
Array map/unmap (mirrors the reference's veles/tests/ coverage, SURVEY.md §4
"Core-engine tests")."""

import numpy as np
import pytest

from znicz_tpu.core.config import Config, apply_overrides, parse_override
from znicz_tpu.core.mutable import Bool
from znicz_tpu.core.units import TrivialUnit, Unit
from znicz_tpu.core.workflow import Repeater, Workflow
from znicz_tpu.memory import Array, roundup


class TestConfig:
    def test_auto_tree(self):
        cfg = Config("r")
        cfg.a.b.c = 3
        assert cfg.a.b.c == 3
        assert cfg.to_dict() == {"a": {"b": {"c": 3}}}

    def test_update_and_get(self):
        cfg = Config("r")
        cfg.update({"x": 1, "sub": {"y": "z"}})
        assert cfg.x == 1
        assert cfg.sub.y == "z"
        assert cfg.get("missing", 42) == 42
        assert cfg.sub.get("y") == "z"

    def test_overrides(self):
        cfg = Config("r")
        apply_overrides(cfg, ["a.b=3", "a.c=hello", "a.d=[1, 2]"])
        assert cfg.a.b == 3
        assert cfg.a.c == "hello"
        assert cfg.a.d == [1, 2]

    def test_parse_override_strips_root(self):
        key, value = parse_override("root.m.lr=0.01")
        assert key == "m.lr" and value == 0.01


class TestBool:
    def test_plain(self):
        b = Bool(False)
        assert not b
        b <<= True
        assert b

    def test_derived_tracks_source(self):
        a = Bool(False)
        n = ~a
        assert n
        a.set(True)
        assert not n

    def test_and_or(self):
        a, b = Bool(True), Bool(False)
        assert not (a & b)
        assert a | b
        b.set(True)
        assert a & b

    def test_on_change(self):
        seen = []
        a = Bool(False)
        a.on_change.append(lambda bb: seen.append(bool(bb)))
        a.set(True)
        a.set(True)  # no change -> no callback
        a.set(False)
        assert seen == [True, False]


class _Recorder(TrivialUnit):
    log_list: list = []

    def run(self):
        _Recorder.log_list.append(self.name)


class TestWorkflowScheduling:
    def setup_method(self):
        _Recorder.log_list = []

    def test_linear_chain(self):
        w = Workflow(name="w")
        a = _Recorder(w, name="a")
        b = _Recorder(w, name="b")
        a.link_from(w.start_point)
        b.link_from(a)
        w.end_point.link_from(b)
        w.initialize(device=_fake_device())
        w.run()
        assert _Recorder.log_list == ["a", "b"]

    def test_and_gate_join(self):
        w = Workflow(name="w")
        a = _Recorder(w, name="a")
        b = _Recorder(w, name="b")
        c = _Recorder(w, name="c")
        a.link_from(w.start_point)
        b.link_from(w.start_point)
        c.link_from(a, b)  # fires only after both
        w.end_point.link_from(c)
        w.initialize(device=_fake_device())
        w.run()
        assert _Recorder.log_list[-1] == "c"
        assert set(_Recorder.log_list) == {"a", "b", "c"}

    def test_repeater_loop_with_gate(self):
        w = Workflow(name="w")
        rep = Repeater(w, name="rep")
        body = _Recorder(w, name="body")
        counter = {"n": 0}

        class Decide(TrivialUnit):
            def run(self):
                counter["n"] += 1
                if counter["n"] >= 3:
                    self.workflow.complete.set(True)

        w.complete = Bool(False)
        dec = Decide(w, name="dec")
        rep.link_from(w.start_point)
        body.link_from(rep)
        dec.link_from(body)
        rep.link_from(dec)          # close the loop
        rep.gate_block = w.complete  # stop looping when complete
        w.end_point.link_from(dec)
        w.end_point.gate_block = ~w.complete
        w.initialize(device=_fake_device())
        w.run()
        assert counter["n"] == 3
        assert _Recorder.log_list == ["body"] * 3

    def test_gate_skip_propagates(self):
        w = Workflow(name="w")
        a = _Recorder(w, name="a")
        b = _Recorder(w, name="b")
        a.gate_skip = Bool(True)
        a.link_from(w.start_point)
        b.link_from(a)
        w.end_point.link_from(b)
        w.initialize(device=_fake_device())
        w.run()
        assert _Recorder.log_list == ["b"]

    def test_gate_block_stops_propagation(self):
        w = Workflow(name="w")
        a = _Recorder(w, name="a")
        b = _Recorder(w, name="b")
        a.gate_block = Bool(True)
        a.link_from(w.start_point)
        b.link_from(a)
        w.end_point.link_from(b)
        w.initialize(device=_fake_device())
        w.run()
        assert _Recorder.log_list == []

    def test_timing_collected(self):
        w = Workflow(name="w")
        a = _Recorder(w, name="a")
        a.link_from(w.start_point)
        w.end_point.link_from(a)
        w.initialize(device=_fake_device())
        w.run()
        assert a.run_count == 1
        assert "a" in w.print_stats()

    def test_graphviz_dump(self):
        w = Workflow(name="w")
        a = _Recorder(w, name="a")
        a.link_from(w.start_point)
        dot = w.generate_graph()
        assert '"start_point" -> "a";' in dot


class TestAttrLinks:
    def test_forwarding(self):
        a = Unit(name="a")
        b = Unit(name="b")
        a.output = 42
        b.link_attrs(a, ("input", "output"))
        assert b.input == 42
        a.output = 43          # rebinding source is visible
        assert b.input == 43

    def test_same_name(self):
        a = Unit(name="a")
        b = Unit(name="b")
        a.minibatch_size = 10
        b.link_attrs(a, "minibatch_size")
        assert b.minibatch_size == 10

    def test_write_detaches_one_way(self):
        a = Unit(name="a")
        b = Unit(name="b")
        a.v = 1
        b.link_attrs(a, "v")
        b.v = 99
        assert b.v == 99 and a.v == 1

    def test_two_way(self):
        a = Unit(name="a")
        b = Unit(name="b")
        a.v = 1
        b.link_attrs(a, "v", two_way=True)
        b.v = 7
        assert a.v == 7


class TestArray:
    def test_roundup(self):
        assert roundup(5, 8) == 8
        assert roundup(16, 8) == 16

    def test_host_device_roundtrip(self):
        arr = Array(np.arange(6, dtype=np.float32).reshape(2, 3))
        dev = arr.devmem
        assert dev.shape == (2, 3)
        host = arr.map_read()
        np.testing.assert_array_equal(host, np.arange(6).reshape(2, 3))

    def test_device_result_adoption(self):
        import jax.numpy as jnp

        arr = Array(np.zeros((2, 2), np.float32))
        arr.devmem = jnp.ones((2, 2), jnp.float32)
        np.testing.assert_array_equal(arr.map_read(), np.ones((2, 2)))

    def test_host_write_syncs_on_unmap(self):
        arr = Array(np.zeros(4, np.float32))
        _ = arr.devmem
        arr.map_write()[:] = 5.0
        np.testing.assert_array_equal(np.asarray(arr.devmem), [5.0] * 4)

    def test_sample_size(self):
        arr = Array(np.zeros((10, 3, 4), np.float32))
        assert arr.sample_size == 12
        assert len(arr) == 10

    def test_empty_read_raises(self):
        with pytest.raises(RuntimeError):
            Array().map_read()

    def test_donated_devmem_recovers_from_host(self):
        """A donating jit may consume a buffer that (CPU backend) aliases
        the Array's devmem; the Array must recover from its host copy —
        and refuse with a clear error when the device value was newer."""
        import jax

        eat = jax.jit(lambda x: x + 1.0, donate_argnums=(0,))
        arr = Array(np.ones((64, 1024), np.float32))
        _ = eat(arr.devmem)                  # donates (and deletes) it
        np.testing.assert_array_equal(
            np.asarray(arr.devmem), np.ones((64, 1024), np.float32))

        arr2 = Array(np.ones(4, np.float32))
        import jax.numpy as jnp

        arr2.devmem = jax.device_put(np.full(4, 2.0, np.float32))
        _ = eat2 = jax.jit(lambda x: x * 2, donate_argnums=(0,))(arr2.devmem)
        if arr2._devmem_deleted():           # small arrays may copy
            with pytest.raises(RuntimeError, match="donat"):
                arr2.map_read()

    def test_host_rewrite_cannot_corrupt_device_value(self):
        """jax.device_put on the CPU backend ZERO-COPIES large aligned
        numpy arrays — after unmap, in-place host writes would mutate the
        'immutable' jax array that queued computations still read (the
        hash-seed-dependent divergence found in r4).  map_write /
        map_invalidate must break the aliasing first."""
        for mapper in ("map_write", "map_invalidate"):
            # large enough to hit the zero-copy path (~60*784 f32 did)
            arr = Array(np.ones((64, 1024), np.float32))
            dev = arr.devmem                  # may alias arr's host buffer
            getattr(arr, mapper)()[...] = 7.0
            np.testing.assert_array_equal(
                np.asarray(dev), np.ones((64, 1024), np.float32),
                err_msg=mapper)
            # and the new host value still reaches the device on unmap
            np.testing.assert_array_equal(
                np.asarray(arr.devmem),
                np.full((64, 1024), 7.0, np.float32), err_msg=mapper)


def _fake_device():
    from znicz_tpu.backends import Device

    return Device(platform="cpu")


class TestPrng:
    def test_named_streams_deterministic(self):
        from znicz_tpu.core import prng

        a1 = prng.get("w1").normal(1.0, (4,))
        prng.reset(1013)
        a2 = prng.get("w1").normal(1.0, (4,))
        np.testing.assert_array_equal(a1, a2)

    def test_streams_independent_of_creation_order(self):
        from znicz_tpu.core import prng

        a = prng.get("alpha").normal(1.0, (3,))
        prng.reset(1013)
        _ = prng.get("beta").normal(1.0, (3,))
        a2 = prng.get("alpha").normal(1.0, (3,))
        np.testing.assert_array_equal(a, a2)


class TestReviewRegressions:
    """Regressions from the first code review."""

    def test_map_write_after_device_adoption_is_writable(self):
        import jax.numpy as jnp

        arr = Array()
        arr.devmem = jnp.zeros((3,), jnp.float32)
        buf = arr.map_write()
        buf[:] = 7.0  # must not raise "assignment destination is read-only"
        np.testing.assert_array_equal(np.asarray(arr.devmem), [7.0] * 3)

    def test_map_invalidate_empty_raises(self):
        with pytest.raises(RuntimeError):
            Array().map_invalidate()

    def test_gate_any_fanin_runs_once_per_wave(self):
        _Recorder.log_list = []
        w = Workflow(name="w")
        a = _Recorder(w, name="a")
        b = _Recorder(w, name="b")
        rep = Repeater(w, name="rep")
        tail = _Recorder(w, name="tail")
        a.link_from(w.start_point)
        b.link_from(w.start_point)
        rep.link_from(a, b)       # both fire in the same wave
        tail.link_from(rep)
        w.end_point.link_from(tail)
        w.initialize(device=_fake_device())
        w.run()
        assert _Recorder.log_list.count("tail") == 1

    def test_prng_key_uses_full_seed(self):
        from znicz_tpu.core import prng

        k1 = prng.get("s1").jax_key(0)
        k2 = prng.get("s2").jax_key(0)
        assert not np.array_equal(np.asarray(k1), np.asarray(k2))


def test_config_defaults_ignores_autovivified_reads():
    """A mere read of a config path must not block later defaults()."""
    from znicz_tpu.core.config import Config

    c = Config("t")
    _ = c.a.b                      # autovivified empty node
    c.defaults({"a": {"b": 5}, "x": 1})
    assert c.a.get("b") == 5
    assert c.get("x") == 1
    c2 = Config("t2")
    c2.a.b = 7                     # user-set leaf wins
    c2.defaults({"a": {"b": 5}})
    assert c2.a.get("b") == 7


def test_workflow_uniquifies_duplicate_unit_names():
    from znicz_tpu.core.units import TrivialUnit
    from znicz_tpu.core.workflow import Workflow

    wf = Workflow(name="dupwf")
    a = TrivialUnit(wf)
    b = TrivialUnit(wf)
    assert a.name != b.name
    assert len({u.name for u in wf.units}) == len(wf.units)
