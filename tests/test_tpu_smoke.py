"""Opt-in REAL-TPU smoke test (VERDICT r3 item 8): platform-specific
breakage (like the axon block_until_ready lie bench.py's barrier works
around) must be catchable outside bench.py.

Skipped by default — the axon tunnel is single-claim, so normal test runs
must never touch it.  Enable with::

    ZNICZ_TPU_SMOKE=1 python -m pytest tests/test_tpu_smoke.py

The test body runs in a SUBPROCESS with a clean environment: this pytest
process is CPU-pinned by conftest (8 virtual devices), so the chip can
only be claimed by a fresh interpreter."""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOKE = textwrap.dedent("""\
    import time

    import numpy as np

    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root

    import jax

    dev = jax.devices()[0]
    assert dev.platform in ("tpu",), dev.platform

    root.common.engine.precision = "bfloat16"
    root.alexnet.loader.minibatch_size = 64
    root.alexnet.loader.n_train = 128
    root.alexnet.loader.n_valid = 64
    root.alexnet.loader.n_classes = 1000
    prng.seed_all(1013)

    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples.alexnet import AlexNetWorkflow

    wf = AlexNetWorkflow()
    wf.initialize(device=None)
    trainer = FusedTrainer(wf)
    step = trainer.make_train_step()
    params = trainer.extract_params()
    vels = trainer.extract_velocities()
    dataset = wf.loader.original_data.devmem
    targets = wf.loader.original_labels.devmem
    idx = np.arange(64, dtype=np.int32) + 64     # train rows
    key = prng.get("fused_trainer").jax_key(0)

    # one fused AlexNet train step on the real chip: loss finite
    params, vels, (loss, n_err, conf) = step(
        params, vels, trainer.hypers(), dataset, targets, idx,
        np.int32(64), key)
    loss_v = float(np.asarray(loss))
    assert np.isfinite(loss_v), loss_v

    # value-materialized barrier semantics (the axon lie): pulling a
    # VALUE that depends on the updated params must take at least the
    # compute time of the dispatched work; block_until_ready alone is
    # NOT trusted on this platform.  Warm timing: value pull >= ~1ms of
    # real work for a full AlexNet step at batch 64 (compute is ~5ms+);
    # a dispatch-rate artifact returns in ~0.2ms.
    t0 = time.perf_counter()
    params, vels, (loss2, _, _) = step(
        params, vels, trainer.hypers(), dataset, targets, idx,
        np.int32(64), key)
    v = float(np.asarray(loss2))             # the barrier
    dt_value = time.perf_counter() - t0
    assert np.isfinite(v)
    print(f"SMOKE_OK loss={loss_v:.4f} warm_value_pull_ms="
          f"{dt_value * 1e3:.2f} device={dev.device_kind}", flush=True)
""")


@pytest.mark.skipif(os.environ.get("ZNICZ_TPU_SMOKE") != "1",
                    reason="opt-in: set ZNICZ_TPU_SMOKE=1 (claims the "
                           "single-claim TPU tunnel)")
def test_real_tpu_fused_step_smoke(tmp_path):
    script = tmp_path / "tpu_smoke.py"
    script.write_text(SMOKE)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, str(script)], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "SMOKE_OK" in proc.stdout, proc.stdout
