"""End-to-end MNIST sample: the BASELINE config[0] parity gate (SURVEY.md §4
functional tests) — seeded run, loss decreases, accuracy beats chance by a
wide margin, snapshot->resume continues identically-shaped training."""

import numpy as np
import pytest

from znicz_tpu.core.config import root


@pytest.fixture
def small_mnist(tmp_path):
    root.mnist.loader.n_train = 600
    root.mnist.loader.n_valid = 120
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 3
    root.mnist.decision.fail_iterations = 0
    root.common.dirs.snapshots = str(tmp_path)
    yield


def test_mnist_trains(small_mnist):
    from znicz_tpu.samples import mnist

    wf = mnist.run()
    dec = wf.decision
    assert dec.epoch_number == 2                     # 3 epochs: 0,1,2
    assert bool(dec.complete)
    train = dec.epoch_metrics[2]
    valid = dec.epoch_metrics[1]
    assert train is not None and valid is not None
    # 10-class chance is 90% err; the glyph task is easy — demand < 40%
    assert valid["err_pct"] < 40.0, valid
    assert dec.best_metric < 0.4
    conf = valid["confusion"]
    assert conf is not None and conf.sum() == 120


def test_mnist_loss_decreases(small_mnist):
    from znicz_tpu.samples import mnist

    losses = []
    wf = mnist.MnistWorkflow()
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    wf.initialize(device=None)
    wf.run()
    assert len(losses) == 3
    assert losses[-1] < losses[0] * 0.8, losses


def test_mnist_deterministic(small_mnist):
    """Same seed => identical loss trajectory (the parity property)."""
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    def one_run():
        prng.reset(1013)
        losses = []
        wf = mnist.MnistWorkflow()
        wf.decision.on_epoch_end.append(
            lambda d: losses.append(d.epoch_metrics[2]["loss"]))
        wf.initialize(device=None)
        wf.run()
        return losses

    a, b = one_run(), one_run()
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_mnist_snapshot_resume(small_mnist, tmp_path):
    from znicz_tpu import snapshotter as snap_mod
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist
    from znicz_tpu.snapshotter import Snapshotter

    wf = mnist.run()
    path = wf.snapshotter.destination
    assert path is not None

    # resume into a fresh workflow; weights must match the snapshot
    prng.reset(1013)
    root.mnist.decision.max_epochs = 5               # train 2 more epochs
    wf2 = mnist.MnistWorkflow()
    wf2.initialize(device=None)
    snap = Snapshotter.load(path)
    snap_mod.restore(wf2, snap)
    w_loaded = np.array(wf2.forwards[0].weights.map_read())
    np.testing.assert_allclose(w_loaded, snap["units"]["fwd0"]["weights"])
    assert wf2.decision.best_metric == snap["decision"]["best_metric"]

    wf2.run()
    assert bool(wf2.decision.complete)
    # resumed training should do no worse than the snapshot
    assert wf2.decision.best_metric <= snap["decision"]["best_metric"] + 1e-9
