"""Extended loaders: image dirs, pickles, HDF5, minibatch saver/replay,
ZeroMQ feed."""

import gzip
import os
import pickle
import threading

import numpy as np
import pytest

from znicz_tpu.loader.base import TRAIN, VALID


def _write_images(base, classes=("cat", "dog"), per_class=3, size=(8, 8)):
    from PIL import Image

    rng = np.random.default_rng(1)
    for cname in classes:
        d = os.path.join(base, cname)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.integers(0, 255, size=size + (3,), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{i}.png"))


def test_image_loader(tmp_path):
    from znicz_tpu.loader.image import FullBatchFileImageLoader

    train = tmp_path / "train"
    valid = tmp_path / "valid"
    _write_images(str(train), per_class=4)
    _write_images(str(valid), per_class=2)
    ld = FullBatchFileImageLoader(
        name="imgld", train_path=str(train), valid_path=str(valid),
        target_shape=(8, 8), minibatch_size=4)
    ld.initialize(device=None)
    assert ld.class_lengths == [0, 4, 8]
    assert ld.class_names == ["cat", "dog"]
    assert ld.original_data.shape == (12, 8, 8, 3)
    assert 0.0 <= ld.original_data.mem.min()
    assert ld.original_data.mem.max() <= 1.0
    ld.run()
    assert ld.minibatch_class == VALID
    assert ld.minibatch_size == 4


def test_pickles_loader(tmp_path):
    from znicz_tpu.loader.pickles import FullBatchPicklesLoader

    rng = np.random.default_rng(2)
    train = (rng.normal(size=(10, 4)).astype(np.float32),
             rng.integers(0, 3, size=10).astype(np.int32))
    with gzip.open(tmp_path / "train.pickle.gz", "wb") as f:
        pickle.dump({"data": train[0], "labels": train[1]}, f)
    ld = FullBatchPicklesLoader(
        name="pkld", train_pickle=str(tmp_path / "train.pickle.gz"),
        minibatch_size=5)
    ld.initialize(device=None)
    assert ld.class_lengths == [0, 0, 10]
    np.testing.assert_allclose(ld.original_data.mem, train[0])


def test_hdf5_loader(tmp_path):
    import h5py

    from znicz_tpu.loader.hdf5 import HDF5Loader

    rng = np.random.default_rng(3)
    path = str(tmp_path / "d.h5")
    with h5py.File(path, "w") as f:
        f["data"] = rng.normal(size=(12, 5)).astype(np.float32)
        f["labels"] = rng.integers(0, 2, size=12).astype(np.int32)
        f["class_lengths"] = np.array([0, 4, 8])
    ld = HDF5Loader(name="h5ld", file_path=path, minibatch_size=4)
    ld.initialize(device=None)
    assert ld.class_lengths == [0, 4, 8]


def test_minibatch_saver_and_replay(tmp_path):
    from znicz_tpu.loader.fullbatch import FullBatchLoader
    from znicz_tpu.loader.saver import MinibatchesLoader, MinibatchesSaver

    ld = FullBatchLoader(name="svld", minibatch_size=4)
    ld.original_data.mem = np.arange(24, dtype=np.float32).reshape(8, 3)
    ld.original_labels.mem = np.arange(8, dtype=np.int32)
    ld.class_lengths = [0, 0, 8]
    ld.initialize(device=None)
    path = str(tmp_path / "mb.pgz")
    sv = MinibatchesSaver(name="sv", file_path=path)
    sv.minibatch_data = ld.minibatch_data
    sv.minibatch_labels = ld.minibatch_labels
    sv.initialize(device=None)
    served = []
    for _ in range(2):
        ld.run()
        sv.minibatch_class = ld.minibatch_class
        sv.minibatch_size = ld.minibatch_size
        sv.run()
        served.append(np.array(ld.minibatch_data.map_read()).copy())
    sv.stop()

    rp = MinibatchesLoader(name="rp", file_path=path)
    rp.initialize(device=None)
    assert rp.class_lengths == [0, 0, 8]
    rp.run()
    np.testing.assert_allclose(np.array(rp.minibatch_data.map_read()),
                               served[0])
    rp.run()
    assert rp.last_minibatch
    rp.run()                                 # wraps to next epoch
    assert rp.epoch_number == 1
    np.testing.assert_allclose(np.array(rp.minibatch_data.map_read()),
                               served[0])


def test_lmdb_codec_roundtrip_and_overflow(tmp_path):
    """MDBWriter -> MDBReader round-trip: key ordering, get(), multi-page
    trees, and values large enough to spill to overflow pages."""
    from znicz_tpu.loader.lmdb import MDBReader, MDBWriter

    rng = np.random.default_rng(7)
    items = {b"%08d" % i: rng.bytes(int(n))
             for i, n in enumerate(rng.integers(1, 9000, size=300))}
    items[b"zz-last"] = b"x" * 20000          # multi-page overflow chain
    path = str(tmp_path / "data.mdb")
    MDBWriter().write(path, items)
    with MDBReader(path) as r:
        assert r.entries == len(items)
        assert r.depth >= 2                    # 300 records span pages
        got = dict(r.items())
        assert got == items
        assert list(got) == sorted(items)      # cursor walks in key order
        for key in (b"%08d" % 0, b"%08d" % 299, b"zz-last"):
            assert r.get(key) == items[key]
        assert r.get(b"absent") is None


def test_lmdb_codec_empty_and_single(tmp_path):
    from znicz_tpu.loader.lmdb import MDBReader, MDBWriter

    path = str(tmp_path / "empty.mdb")
    MDBWriter().write(path, {})
    with MDBReader(path) as r:
        assert r.entries == 0
        assert list(r.items()) == []
        assert r.get(b"a") is None

    path = str(tmp_path / "one.mdb")
    MDBWriter().write(path, {b"k": b"v"})
    with MDBReader(path) as r:
        assert r.entries == 1 and r.depth == 1
        assert r.get(b"k") == b"v"


def test_lmdb_loader(tmp_path):
    """The SURVEY §2.1 loader-family test pattern: write a tiny LMDB
    in-test, load it, assert the class walk + data round-trip."""
    from znicz_tpu.loader.lmdb import LMDBLoader, write_dataset

    rng = np.random.default_rng(11)
    data = rng.normal(size=(12, 6)).astype(np.float32)
    labels = rng.integers(0, 3, size=12).astype(np.int32)
    path = str(tmp_path / "ds.mdb")
    write_dataset(path, data, labels, class_lengths=[0, 4, 8])

    ld = LMDBLoader(name="lmdbld", file_path=path, minibatch_size=4)
    ld.initialize(device=None)
    assert ld.class_lengths == [0, 4, 8]
    np.testing.assert_allclose(ld.original_data.mem, data)
    np.testing.assert_array_equal(ld.original_labels.mem, labels)
    ld.run()
    assert ld.minibatch_class == VALID         # VALID walks before TRAIN
    assert ld.minibatch_size == 4
    ld.run()
    assert ld.minibatch_class == TRAIN


def test_zmq_loader():
    import zmq

    from znicz_tpu.loader.zmq_loader import ZeroMQLoader

    endpoint = "tcp://127.0.0.1:17755"
    ld = ZeroMQLoader(name="zmqld", endpoint=endpoint, bind=True)
    ld.initialize(device=None)

    def feeder():
        ctx = zmq.Context.instance()
        sock = ctx.socket(zmq.PUSH)
        sock.connect(endpoint)
        rec = {"data": np.ones((2, 3), np.float32),
               "labels": np.array([0, 1], np.int32),
               "class": TRAIN, "size": 2, "last": True}
        sock.send(pickle.dumps(rec))
        sock.send(pickle.dumps({"end": True}))
        # linger: a PUSH connect is async and close(0) DROPS queued
        # messages that raced the TCP handshake — on a loaded host the
        # feeder would vanish before its two records ever hit the wire
        sock.close(30_000)

    t = threading.Thread(target=feeder)
    t.start()
    ld.run()
    assert ld.minibatch_size == 2
    assert ld.last_minibatch
    np.testing.assert_allclose(np.array(ld.minibatch_data.map_read()),
                               np.ones((2, 3)))
    ld.run()
    assert ld.finished
    t.join()
    ld.stop()