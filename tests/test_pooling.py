"""Pooling forward/backward numerics incl. partial edge windows, offset
recording, and the stochastic variants' mask-reuse contract."""

import numpy as np

from znicz_tpu.gd_pooling import (
    GDAvgPooling,
    GDMaxPooling,
    GDStochasticPooling,
)
from znicz_tpu.memory import Array
from znicz_tpu.pooling import (
    AvgPooling,
    MaxAbsPooling,
    MaxPooling,
    StochasticPooling,
)


def test_max_pooling_matches_numpy():
    rng = np.random.default_rng(4)
    x = rng.normal(size=(2, 6, 6, 3)).astype(np.float32)
    p = MaxPooling(name="mp", kx=2, ky=2)
    p.input = Array(x)
    p.initialize(device=None)
    p.run()
    got = np.array(p.output.map_read())
    want = x.reshape(2, 3, 2, 3, 2, 3).max(axis=(2, 4))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_max_pooling_partial_edge_windows():
    """5x5 input, 2x2 stride-2 pool -> 3x3 output with partial edges."""
    x = np.arange(25, dtype=np.float32).reshape(1, 5, 5, 1)
    p = MaxPooling(name="mpe", kx=2, ky=2)
    p.input = Array(x)
    p.initialize(device=None)
    assert p.output_shape_for((1, 5, 5, 1)) == (1, 3, 3, 1)
    p.run()
    got = np.array(p.output.map_read())[0, :, :, 0]
    want = np.array([[6, 8, 9], [16, 18, 19], [21, 23, 24]], np.float32)
    np.testing.assert_allclose(got, want)


def test_maxabs_pooling_keeps_sign():
    x = np.array([[[[1.0], [-5.0]], [[2.0], [3.0]]]], np.float32)
    p = MaxAbsPooling(name="map", kx=2, ky=2)
    p.input = Array(x)
    p.initialize(device=None)
    p.run()
    assert float(np.array(p.output.map_read()).reshape(())) == -5.0


def test_avg_pooling_partial_window_counts():
    x = np.ones((1, 3, 3, 1), np.float32)
    p = AvgPooling(name="ap", kx=2, ky=2)
    p.input = Array(x)
    p.initialize(device=None)
    p.run()
    got = np.array(p.output.map_read())[0, :, :, 0]
    # full windows avg 1; partial edge windows must also avg 1 (divide by
    # real count, not kx*ky)
    np.testing.assert_allclose(got, np.ones((2, 2)), rtol=1e-6)


def test_gd_max_pooling_routes_err_to_argmax():
    rng = np.random.default_rng(6)
    x = rng.normal(size=(2, 4, 4, 2)).astype(np.float32)
    p = MaxPooling(name="gmp", kx=2, ky=2)
    p.input = Array(x)
    p.initialize(device=None)
    p.run()
    err = rng.normal(size=(2, 2, 2, 2)).astype(np.float32)
    gd = GDMaxPooling(name="gmpgd", forward=p)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    gd.run()
    got = np.array(gd.err_input.map_read())
    # oracle: scatter err to argmax positions
    want = np.zeros_like(x)
    for b in range(2):
        for oy in range(2):
            for ox in range(2):
                for c in range(2):
                    win = x[b, oy*2:oy*2+2, ox*2:ox*2+2, c]
                    dy, dx = np.unravel_index(np.argmax(win), (2, 2))
                    want[b, oy*2+dy, ox*2+dx, c] += err[b, oy, ox, c]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_gd_avg_pooling_is_vjp_of_forward():
    rng = np.random.default_rng(8)
    x = rng.normal(size=(1, 4, 4, 1)).astype(np.float32)
    p = AvgPooling(name="gap", kx=2, ky=2)
    p.input = Array(x)
    p.initialize(device=None)
    p.run()
    err = rng.normal(size=(1, 2, 2, 1)).astype(np.float32)
    gd = GDAvgPooling(name="gapgd", forward=p)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    gd.run()
    got = np.array(gd.err_input.map_read())
    want = np.repeat(np.repeat(err, 2, axis=1), 2, axis=2) / 4.0
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_stochastic_pooling_mask_reuse_and_eval_mode():
    rng = np.random.default_rng(9)
    x = np.abs(rng.normal(size=(2, 4, 4, 2))).astype(np.float32)
    p = StochasticPooling(name="sp", kx=2, ky=2)
    p.input = Array(x)
    p.minibatch_class = 2                 # TRAIN
    p.initialize(device=None)
    p.run()
    off = np.array(p.input_offset.map_read())
    out = np.array(p.output.map_read())
    # sampled offsets select actual window values
    for b in range(2):
        for oy in range(2):
            for ox in range(2):
                for c in range(2):
                    win = x[b, oy*2:oy*2+2, ox*2:ox*2+2, c].reshape(-1)
                    assert out[b, oy, ox, c] == win[off[b, oy, ox, c]]
    # backward scatters via the SAME offsets (mask reuse, not resampled)
    err = rng.normal(size=out.shape).astype(np.float32)
    gd = GDStochasticPooling(name="spgd", forward=p)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    gd.run()
    got = np.array(gd.err_input.map_read())
    want = np.zeros_like(x)
    for b in range(2):
        for oy in range(2):
            for ox in range(2):
                for c in range(2):
                    dy, dx = divmod(int(off[b, oy, ox, c]), 2)
                    want[b, oy*2+dy, ox*2+dx, c] += err[b, oy, ox, c]
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # eval mode: deterministic expectation, two runs agree
    p.minibatch_class = 1
    p.run()
    a = np.array(p.output.map_read()).copy()
    p.run()
    b2 = np.array(p.output.map_read())
    np.testing.assert_allclose(a, b2)
    # expectation oracle for one window
    win = x[0, 0:2, 0:2, 0].reshape(-1)
    wsum = win.sum()
    np.testing.assert_allclose(a[0, 0, 0, 0], float((win * win).sum() / wsum),
                               rtol=1e-5)


def test_masked_maxpool_bwd_matches_sas_when_unique():
    """The scatter-free masked max-pool backward (opt-in pool_bwd="mask")
    must equal XLA's select_and_scatter gradient EXACTLY whenever window
    maxima are unique, and conserve gradient mass under ties (dy split
    among tied maxima — documented semantic difference)."""
    import jax
    import jax.numpy as jnp

    from znicz_tpu.pooling import _masked_maxpool, pool_output_hw

    rng = np.random.default_rng(5)
    ky = kx = 3
    sy = sx = 2
    # unique maxima: continuous random values, ties have measure zero
    x = jnp.asarray(rng.standard_normal((2, 13, 13, 4)), jnp.float32)
    f_mask = _masked_maxpool(ky, kx, sy, sx)

    def f_sas(x):
        oh, ow = pool_output_hw(x.shape[1], x.shape[2], ky, kx, (sy, sx))
        ph, pw = (oh - 1) * sy + ky, (ow - 1) * sx + kx
        return jax.lax.reduce_window(
            x, x.dtype.type(-np.inf), jax.lax.max,
            window_dimensions=(1, ky, kx, 1),
            window_strides=(1, sy, sx, 1),
            padding=((0, 0), (0, ph - x.shape[1]), (0, pw - x.shape[2]),
                     (0, 0)))

    np.testing.assert_array_equal(np.asarray(f_mask(x)),
                                  np.asarray(f_sas(x)))
    dy = jnp.asarray(rng.standard_normal(f_sas(x).shape), jnp.float32)

    def loss(f):
        return lambda x: jnp.vdot(f(x), dy)

    g_mask = np.asarray(jax.grad(loss(f_mask))(x))
    g_sas = np.asarray(jax.grad(loss(f_sas))(x))
    np.testing.assert_allclose(g_mask, g_sas, rtol=1e-6, atol=1e-6)

    # ties (ReLU-like zeros): mass conserved per window even when split
    xt = jnp.zeros((1, 5, 5, 1), jnp.float32)
    dyt = jnp.asarray(rng.standard_normal(f_mask(xt).shape), jnp.float32)
    g_t = np.asarray(jax.grad(lambda x: jnp.vdot(f_mask(x), dyt))(xt))
    # every window's dy mass lands somewhere in dx
    np.testing.assert_allclose(g_t.sum(), float(np.asarray(dyt).sum()),
                               rtol=1e-5)
