"""znicz-lint (ISSUE 9): the checkers themselves cannot silently rot.

Every rule is exercised on fixture snippets — at least one known TRUE
POSITIVE (the checker fires) and one known TRUE NEGATIVE (it stays
quiet) each, including the lock-guarded-write negative, the
``.get(variable)`` dynamic-read negative, and the pragma/baseline
suppression paths.  The final test is the tier-1 gate: the whole
analyzer over ``znicz_tpu/`` must come back with ZERO unbaselined
findings, inside a lean wall-clock budget.

(The config-knob alias-resolution fixtures live with the historical
lint names in tests/test_no_adhoc_counters.py.)
"""

import json
import pathlib
import textwrap
import time

from znicz_tpu.analysis import (DEFAULT_BASELINE, Finding, Module, run)
from znicz_tpu.analysis.__main__ import main as cli_main
from znicz_tpu.analysis.config_knob import ConfigKnobChecker
from znicz_tpu.analysis.counters import CounterRegistryChecker
from znicz_tpu.analysis.jit_purity import JitPurityChecker
from znicz_tpu.analysis.threads import ThreadSharedStateChecker

PKG = pathlib.Path(__file__).resolve().parent.parent / "znicz_tpu"


def _module(code, rel="fixture.py"):
    return Module(pathlib.Path(rel), rel, textwrap.dedent(code))


def _check(checker, code, rel="fixture.py"):
    return list(checker.check(_module(code, rel)))


# -- thread-shared-state -------------------------------------------------------

_RACY = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.stats = {}

        def start(self):
            self._thread = threading.Thread(target=self._loop)
            self._thread.start()

        def _loop(self):
            self.stats["n"] = 1          # unlocked worker mutation

        def snapshot(self):
            return dict(self.stats)      # ... read on the caller thread
"""


def test_thread_shared_state_true_positive():
    found = _check(ThreadSharedStateChecker(), _RACY)
    assert len(found) == 1
    assert "Worker.stats" in found[0].message
    assert "_loop()" in found[0].message
    assert "snapshot()" in found[0].message


def test_thread_shared_state_emits_per_write_site():
    """One finding PER unlocked write site — a NEW mutation of an
    already-baselined attribute must be the N+1th identical finding
    (live under the baseline count cap), not deduped away."""
    found = _check(ThreadSharedStateChecker(), """
        import threading

        class W:
            def start(self):
                threading.Thread(target=self._loop).start()
            def _loop(self):
                self.accepted = 1
                self.accepted = 2
            def outcomes(self):
                return self.accepted
    """)
    assert len(found) == 2
    assert found[0].key == found[1].key          # same line-free key
    assert found[0].line != found[1].line


def test_thread_shared_state_lock_guarded_negative():
    """The same shape with the write under ``with self._lock`` is the
    canonical true negative."""
    found = _check(ThreadSharedStateChecker(), """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.stats = {}

            def start(self):
                self._thread = threading.Thread(target=self._loop)
                self._thread.start()

            def _loop(self):
                with self._lock:
                    self.stats["n"] = 1

            def snapshot(self):
                with self._lock:
                    return dict(self.stats)
    """)
    assert not found, [f.message for f in found]


def test_thread_shared_state_more_negatives():
    # no thread spawned at all -> no worker, no findings
    assert not _check(ThreadSharedStateChecker(), """
        class Plain:
            def f(self):
                self.stats = {}
            def g(self):
                return self.stats
    """)
    # Event/Queue traffic is the thread-safe API, not shared raw state;
    # attrs only the worker touches are private to it
    assert not _check(ThreadSharedStateChecker(), """
        import threading, queue

        class Worker:
            def __init__(self):
                self._stop = threading.Event()
                self._q = queue.Queue()

            def start(self):
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self._scratch = []            # worker-private
                self._scratch.append(1)
                while not self._stop.is_set():
                    self._q.put(1)

            def stop(self):
                self._stop.set()
                return self._q.get()
    """)
    # transitive: the helper called FROM the worker loop is worker code
    found = _check(ThreadSharedStateChecker(), """
        import threading

        class Worker:
            def start(self):
                threading.Thread(target=self._loop).start()
            def _loop(self):
                self._tick()
            def _tick(self):
                self.done_jobs = 1
            def progress(self):
                return self.done_jobs
    """)
    assert len(found) == 1 and "_tick()" in found[0].message


# -- jit-purity ----------------------------------------------------------------


def test_jit_purity_true_positives():
    found = _check(JitPurityChecker(), """
        import jax

        @jax.jit
        def step(x):
            print("stepping")         # side effect
            counters.inc()            # telemetry at trace time
            state.last = x            # attribute mutation
            return float(x) + x.item()   # two tracer leaks
    """)
    kinds = "\n".join(f.message for f in found)
    assert len(found) == 5, kinds
    assert "print()" in kinds and ".inc()" in kinds
    assert "attribute mutation" in kinds
    assert "float()" in kinds and ".item()" in kinds


def test_jit_purity_discovery_forms():
    """jit-by-assignment, defvjp-registered bwd, and pallas kernels are
    all discovered; the wrapper-shares-the-name shape is NOT swept in."""
    checker = JitPurityChecker()
    found = _check(checker, """
        import jax

        def f(x):
            print(x)
            return x
        g = jax.jit(f)
    """)
    assert len(found) == 1
    found = _check(checker, """
        import jax

        def bwd(res, ct):
            print(ct)
            return (ct,)
        h.defvjp(fwd, bwd)
    """)
    assert len(found) == 1
    found = _check(checker, """
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            print("in kernel")
            o_ref[...] = x_ref[...]

        def call(x):
            return pl.pallas_call(kernel, out_shape=None)(x)
    """)
    assert len(found) == 1
    # public wrapper named like the inner traced def (ops/lrn_pallas
    # shape): the int()/float() hyper normalization in the WRAPPER is
    # trace-free and must stay quiet
    assert not _check(checker, """
        import functools, jax

        def _make():
            @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
            def lrn(x, n):
                return x * n
            return lrn

        def lrn(x, n=5):
            return _make()(x, int(n))
    """)


def test_jit_purity_recompile_hazards():
    checker = JitPurityChecker()
    found = _check(checker, """
        import jax

        def f(x, shape):
            return x
        g = jax.jit(f, static_argnames=("shape",))
        y = g(x, shape=[1, 2])        # unhashable static -> TypeError
        z = g(x, f"{n}x{m}")          # f-string-derived static
    """)
    msgs = "\n".join(f.message for f in found)
    assert len(found) == 2, msgs
    assert "unhashable list" in msgs and "f-string" in msgs
    # hashable statics at call sites are the true negative
    assert not _check(checker, """
        import jax

        def f(x, shape):
            return x
        g = jax.jit(f, static_argnames=("shape",))
        y = g(x, shape=(1, 2))
    """)


def test_jit_purity_true_negative_pure_fn():
    assert not _check(JitPurityChecker(), """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(params, x):
            y = jnp.dot(params, x)
            return y / jnp.float32(2)
    """)
    # impure code OUTSIDE any traced function is none of this rule's
    # business
    assert not _check(JitPurityChecker(), """
        def host_loop(x):
            print(x)
            return float(x)
    """)


# -- config-knob (alias fixtures live in test_no_adhoc_counters.py) ------------


def test_config_knob_scope_rules():
    """Class-body subtree bindings are NOT trackable locals (reads go
    through self.<name> from anywhere) — the binding itself is flagged
    as an escape; module-level aliases are visible inside functions
    defined textually ABOVE the assignment (defs run after the module
    body finishes)."""
    checker = ConfigKnobChecker(PKG)
    found = _check(checker, """
        from znicz_tpu.core.config import root
        class C:
            ADM = root.common.serving.admission
            def f(self):
                return self.ADM.get("rate_limi", 0)
    """)
    assert len(found) == 1
    assert "stored outside the local scope" in found[0].message
    found = _check(checker, """
        from znicz_tpu.core.config import root
        def f():
            return adm.get("rate_limi", 0)
        adm = root.common.serving.admission
    """)
    assert len(found) == 1 and "rate_limi" in found[0].message


def test_config_knob_fixture_pair():
    checker = ConfigKnobChecker(PKG)
    found = _check(checker, """
        from znicz_tpu.core.config import root
        a = root.common.engine.get("bogus", 1)
    """)
    assert len(found) == 1 and "bogus" in found[0].message
    assert not _check(checker, """
        from znicz_tpu.core.config import root
        a = root.common.engine.get("scan_chunk", 8)
        b = root.common.serving.get(name, DEFAULTS[name])   # dynamic
        c = root.mnistr.decision.max_epochs                 # other tree
    """)


# -- counter-registry ----------------------------------------------------------


def test_counter_registry_fixture_pair():
    checker = CounterRegistryChecker(allowlist=())
    found = _check(checker, """
        class S:
            def f(self):
                self.bad_frames += 1
    """)
    assert len(found) == 1
    assert not _check(checker, """
        class S:
            def f(self):
                self._pos += 1
                self.timestamp += dt     # no counter suffix
    """)
    # the telemetry registry implements itself
    assert not _check(checker, """
        class Counter:
            def inc(self):
                self.count += 1
    """, rel="telemetry/metrics.py")
    # allowlisted state with a justification stays quiet
    assert not _check(CounterRegistryChecker(
        allowlist={("kohonen.py", "total")}), """
        class K:
            def f(self):
                self.total += batch
    """, rel="kohonen.py")


# -- suppression paths ---------------------------------------------------------


def test_pragma_suppression(tmp_path):
    code = textwrap.dedent("""
        class S:
            def f(self):
                self.bad_frames += 1   # znicz: ignore[counter-registry]
                self.good_frames += 1
    """)
    (tmp_path / "mod.py").write_text(code)
    analysis = run(tmp_path, rules=["counter-registry"],
                   baseline_path=None)
    assert len(analysis.findings) == 1          # unpragma'd line stays
    assert "good_frames" in analysis.findings[0].message
    assert len(analysis.pragma_suppressed) == 1
    # pragma on the line ABOVE works too; the wrong rule name does not
    (tmp_path / "mod.py").write_text(textwrap.dedent("""
        class S:
            def f(self):
                # znicz: ignore[counter-registry]
                self.bad_frames += 1
                # znicz: ignore[thread-shared-state]
                self.good_frames += 1
    """))
    analysis = run(tmp_path, rules=["counter-registry"],
                   baseline_path=None)
    assert len(analysis.findings) == 1
    assert "good_frames" in analysis.findings[0].message


def test_baseline_suppression_and_count_cap(tmp_path):
    (tmp_path / "mod.py").write_text(textwrap.dedent("""
        class S:
            def f(self):
                self.bad_frames += 1
            def g(self):
                self.bad_frames += 1
    """))
    analysis = run(tmp_path, rules=["counter-registry"],
                   baseline_path=None)
    assert len(analysis.findings) == 2
    entry = dict(analysis.findings[0].to_json(),
                 reason="fixture: accepted for the test")
    del entry["line"], entry["severity"]
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"entries": [entry]}))
    # count defaults to 1: one finding absorbed, the second stays LIVE
    analysis = run(tmp_path, rules=["counter-registry"],
                   baseline_path=baseline)
    assert len(analysis.findings) == 1
    assert len(analysis.baselined) == 1
    assert analysis.baselined[0][1] == "fixture: accepted for the test"
    # count=2 absorbs both; a stale entry (nothing matches) is reported
    baseline.write_text(json.dumps({"entries": [
        dict(entry, count=2),
        dict(entry, message="never matches anything", reason="stale")]}))
    analysis = run(tmp_path, rules=["counter-registry"],
                   baseline_path=baseline)
    assert not analysis.findings and len(analysis.baselined) == 2
    assert len(analysis.stale_baseline) == 1
    # a stale entry fails the gate: CI must not stay green behind a
    # dead entry a regression could crawl back through
    assert not analysis.clean
    assert "znicz-lint: clean" not in analysis.render_text()
    rc = cli_main([str(tmp_path), "--rules", "counter-registry",
                   "--baseline", str(baseline)])
    assert rc == 1


# -- the tier-1 gate -----------------------------------------------------------


def test_package_is_clean_under_the_analyzer():
    """THE gate (ISSUE 9 acceptance): zero unbaselined findings over
    znicz_tpu/, every baseline entry still matching something, inside a
    lean wall-clock budget (<15s; shows up in the conftest 10-slowest
    table if it ever grows)."""
    t0 = time.perf_counter()
    analysis = run(PKG)
    wall = time.perf_counter() - t0
    assert not analysis.parse_errors, \
        [f.render() for f in analysis.parse_errors]
    assert not analysis.findings, "unbaselined findings — fix them or " \
        "baseline with a justification (znicz_tpu/analysis/" \
        "baseline.json):\n  " + "\n  ".join(
            f.render() for f in analysis.findings)
    assert not analysis.stale_baseline, (
        "stale baseline entries (matched nothing — the finding was "
        "fixed or the message drifted): %r" % analysis.stale_baseline)
    assert analysis.baselined, "the committed baseline went empty — " \
        "if every finding is truly fixed, delete the entries AND this " \
        "assert together"
    assert wall < 15.0, f"analyzer self-run took {wall:.1f}s"


def test_cli_text_and_json(tmp_path, capsys):
    # the package gate through the real CLI entry point
    assert cli_main([]) == 0
    out = capsys.readouterr().out
    assert "znicz-lint: clean" in out
    # --json over a dirty fixture tree: exit 1 + machine-readable counts
    (tmp_path / "mod.py").write_text(
        "class S:\n    def f(self):\n        self.bad_frames += 1\n")
    rc = cli_main([str(tmp_path), "--json", "--baseline", "none",
                   "--rules", "counter-registry"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert data["clean"] is False
    assert data["counts"] == {"counter-registry": 1}
    assert data["findings"][0]["path"] == "mod.py"
    assert data["findings"][0]["line"] == 3
    # per-rule selection rejects unknown rules loudly
    try:
        cli_main(["--rules", "bogus-rule"])
    except SystemExit as exc:
        assert exc.code == 2
    else:  # pragma: no cover
        raise AssertionError("unknown rule accepted")


def test_default_baseline_is_the_committed_file():
    assert DEFAULT_BASELINE == PKG / "analysis" / "baseline.json"
    assert DEFAULT_BASELINE.exists()
    entries = json.loads(DEFAULT_BASELINE.read_text())["entries"]
    assert all(e.get("reason") for e in entries), \
        "every baseline entry needs its one-line justification"


def test_finding_render_and_key():
    f = Finding("r", "a/b.py", 7, "msg")
    assert f.render() == "a/b.py:7: r: msg"
    assert f.key == ("r", "a/b.py", "msg")
    assert f.to_json()["severity"] == "error"


# -- transport-core (ISSUE 14: the unified dataplane) --------------------------

_ZMQ_FORKED = """
    import zmq

    def serve(self):
        ctx = zmq.Context.instance()
        sock = ctx.socket(zmq.ROUTER)
        sock.bind("tcp://127.0.0.1:5555")
        poller = zmq.Poller()
        poller.register(sock, zmq.POLLIN)

    class S:
        def up(self):
            import zmq
            self._sock = zmq.Context.instance().socket(zmq.PULL)
            self._sock.bind("inproc://x")
"""

_ZMQ_RIDES_COMMON = """
    import zmq

    def serve(self):
        from znicz_tpu.network_common import bind_with_retry, make_poller

        ctx = zmq.Context.instance()
        sock = ctx.socket(zmq.ROUTER)
        bind_with_retry(sock, "tcp://127.0.0.1:5555")
        back = ctx.socket(zmq.DEALER)
        back.connect("tcp://127.0.0.1:5556")      # connect: no race
        poller = make_poller(sock, back)

    def not_a_socket(self):
        server = HTTPServer()
        server.bind(("127.0.0.1", 0))             # not a ZMQ socket
"""

_DISPATCH_FORKED = """
    import zmq

    def serve(self):
        from znicz_tpu.network_common import bind_with_retry, make_poller

        ctx = zmq.Context.instance()
        sock = ctx.socket(zmq.ROUTER)
        bind_with_retry(sock, "tcp://127.0.0.1:5555")
        poller = make_poller(sock)
        while True:
            if poller.poll(20):                  # hand-rolled dispatch
                sock.recv_multipart()
"""

_RECONNECT_FORKED = """
    import time
    import zmq

    def fetch(self, ctx):
        for attempt in range(8):
            sock = ctx.socket(zmq.REQ)           # fresh-socket retry
            try:
                sock.send(b"x")
                return sock.recv()
            except zmq.Again:
                time.sleep(0.25 * (2 ** attempt))  # raw backoff too
            finally:
                sock.close(0)
"""

_CLIENT_RIDES_CORE = """
    def fetch(self, endpoint):
        from znicz_tpu.transport import Endpoint, RetryPolicy

        ep = Endpoint(endpoint, retry=RetryPolicy.for_training_client())
        for attempt in range(8):
            try:
                return ep.rpc_message({"cmd": "job"})
            except Exception:
                ep.backoff(attempt + 1)

    def single_socket_wait(self):
        # .poll on a bare SOCKET is a wait, not a dispatch loop
        while self._sock.poll(20):
            self._sock.recv()

    def lifecycle(self, ctx):
        import zmq
        sock = ctx.socket(zmq.DEALER)            # created ONCE,
        try:                                     # closed once: not a
            sock.connect("tcp://127.0.0.1:1")    # reconnect cycle
        finally:
            sock.close(0)
"""


def test_transport_core_fixture_pairs():
    from znicz_tpu.analysis.transport_core import TransportCoreChecker

    findings = _check(TransportCoreChecker(), _ZMQ_FORKED)
    # two raw binds (name + self-attr receivers) and one raw Poller
    assert len(findings) == 3
    assert sum("Poller" in f.message for f in findings) == 1
    assert sum("bind_with_retry" in f.message for f in findings) == 2
    assert not _check(TransportCoreChecker(), _ZMQ_RIDES_COMMON)
    # network_common and the transport package itself are sanctioned
    assert not _check(TransportCoreChecker(), _ZMQ_FORKED,
                      rel="network_common.py")
    assert not _check(TransportCoreChecker(), _DISPATCH_FORKED,
                      rel="transport/core.py")


def test_transport_core_dispatch_and_reconnect():
    from znicz_tpu.analysis.transport_core import TransportCoreChecker

    dispatch = _check(TransportCoreChecker(), _DISPATCH_FORKED)
    assert sum("dispatch loop" in f.message for f in dispatch) == 1
    reconnect = _check(TransportCoreChecker(), _RECONNECT_FORKED)
    assert sum("reconnect cycle" in f.message for f in reconnect) == 1
    assert sum("backoff sleep" in f.message for f in reconnect) == 1
    assert not _check(TransportCoreChecker(), _CLIENT_RIDES_CORE)
