"""Fused AlexNet tail + bf16 compute path (ISSUE 7): every new fused
stage (conv3-5 bias+StrictRELU, FC bias+ReLU+dropout epilogue,
softmax-xent loss+grad epilogue) has interpret-mode fwd/bwd parity vs the
composed ops and finite-difference checks on this CPU-only box; the
matcher/plan respects the ``fused_tail`` flag and yields to the
conv-block kernel's span; e2e FusedTrainer parity fused-tail on/off (f32
and bf16); the ``compute_dtype`` knob (canonical spelling of the legacy
``precision``); the bf16 non-finite-delta / quarantine interaction; the
staging+bf16 zero-recompile proof; and the XLA latency-hiding flag
wiring."""

import numpy as np
import pytest

from znicz_tpu.core.config import root

from tests.test_fused import fresh_mnist


def _rand(shape, seed, scale=1.0):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=shape).astype(np.float32) * scale)


# -- stage 1: conv3-5 bias+StrictRELU (Pallas, interpret mode here) ------------


def test_bias_relu_forward_and_grad_match_composed():
    import jax
    import jax.numpy as jnp

    from znicz_tpu.pallas_fused_block import fused_bias_relu

    x = _rand((2, 5, 5, 8), 3, 2.0)
    b = _rand((8,), 4, 0.1)
    np.testing.assert_allclose(
        np.asarray(fused_bias_relu(x, b)),
        np.asarray(jnp.maximum(x + b, 0.0)), rtol=1e-6, atol=1e-7)
    cot = _rand((2, 5, 5, 8), 5)
    gx, gb = jax.grad(
        lambda xx, bb: jnp.sum(fused_bias_relu(xx, bb) * cot),
        argnums=(0, 1))(x, b)
    rx, rb = jax.grad(
        lambda xx, bb: jnp.sum(jnp.maximum(xx + bb, 0.0) * cot),
        argnums=(0, 1))(x, b)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb), rtol=1e-5,
                               atol=1e-5)
    # bf16 operands: bf16 out, f32 internal math (block-kernel policy)
    xb = x.astype(jnp.bfloat16)
    bb16 = b.astype(jnp.bfloat16)
    out = fused_bias_relu(xb, bb16)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out.astype(jnp.float32)),
        np.asarray(jnp.maximum(xb.astype(jnp.float32)
                               + bb16.astype(jnp.float32), 0.0)),
        rtol=2e-2, atol=2e-2)


def test_bias_relu_finite_differences():
    import jax
    import jax.numpy as jnp

    from znicz_tpu.pallas_fused_block import fused_bias_relu

    # keep pre-activations off the ReLU kink (measure-zero; the composed
    # parity above covers tie behavior)
    x = _rand((1, 4, 4, 4), 21)
    x = jnp.sign(x) * (jnp.abs(x) + 0.3)
    b = _rand((4,), 22, 0.05)
    cot = _rand((1, 4, 4, 4), 23)

    def loss(xx, bb):
        return jnp.sum(fused_bias_relu(xx, bb) * cot)

    gx, gb = jax.grad(loss, argnums=(0, 1))(x, b)
    eps = 1e-3
    # probe count is budget-bound (each interpret-mode eval is ~0.3s);
    # the composed-parity test above is the dense check
    for idx in [(0, 0, 0, 0), (0, 2, 3, 1)]:
        e = jnp.zeros_like(x).at[idx].set(eps)
        fd = (float(loss(x + e, b)) - float(loss(x - e, b))) / (2 * eps)
        assert abs(fd - float(gx[idx])) <= 5e-2 * max(1.0, abs(fd))
    e = jnp.zeros_like(b).at[3].set(eps)
    fd = (float(loss(x, b + e)) - float(loss(x, b - e))) / (2 * eps)
    assert abs(fd - float(gb[3])) <= 5e-2 * max(1.0, abs(fd))


# -- stage 2: FC bias+ReLU+dropout epilogue ------------------------------------


def test_fc_epilogue_matches_composed_and_grads():
    import jax
    import jax.numpy as jnp

    from znicz_tpu.dropout import DropoutForward
    from znicz_tpu.pallas_fused_block import fused_fc_epilogue

    y = _rand((4, 16), 31)
    b = _rand((16,), 32, 0.1)
    key = jax.random.PRNGKey(7)
    ratio = 0.5

    def composed(yy, bb):
        r = jnp.maximum(yy + bb, 0.0)
        # the SAME bernoulli draw the unit path's DropoutForward makes —
        # mask parity is bit-exact, not distributional
        return r * DropoutForward.make_mask(key, y.shape, ratio)

    out = fused_fc_epilogue(y, b, key, ratio, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(composed(y, b)),
                               rtol=1e-6)
    cot = _rand((4, 16), 33)
    g = jax.grad(lambda a, c: jnp.sum(
        fused_fc_epilogue(a, c, key, ratio, True) * cot),
        argnums=(0, 1))(y, b)
    r = jax.grad(lambda a, c: jnp.sum(composed(a, c) * cot),
                 argnums=(0, 1))(y, b)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(r[0]),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g[1]), np.asarray(r[1]),
                               rtol=1e-5, atol=1e-5)
    # eval / no-dropout: plain bias+relu, key unused (and allowed None)
    np.testing.assert_allclose(
        np.asarray(fused_fc_epilogue(y, b, None, ratio, False)),
        np.asarray(jnp.maximum(y + b, 0.0)), rtol=1e-6)


def test_fc_epilogue_finite_differences():
    import jax
    import jax.numpy as jnp

    from znicz_tpu.pallas_fused_block import fused_fc_epilogue

    y = _rand((2, 8), 41)
    y = jnp.sign(y) * (jnp.abs(y) + 0.3)       # off the kink
    b = _rand((8,), 42, 0.05)
    key = jax.random.PRNGKey(11)
    cot = _rand((2, 8), 43)

    def loss(yy, bb):
        return jnp.sum(fused_fc_epilogue(yy, bb, key, 0.5, True) * cot)

    gy, gb = jax.grad(loss, argnums=(0, 1))(y, b)
    eps = 1e-3
    for idx in [(0, 0), (1, 5)]:
        e = jnp.zeros_like(y).at[idx].set(eps)
        fd = (float(loss(y + e, b)) - float(loss(y - e, b))) / (2 * eps)
        assert abs(fd - float(gy[idx])) <= 5e-2 * max(1.0, abs(fd))
    e = jnp.zeros_like(b).at[5].set(eps)
    fd = (float(loss(y, b + e)) - float(loss(y, b - e))) / (2 * eps)
    assert abs(fd - float(gb[5])) <= 5e-2 * max(1.0, abs(fd))


# -- stage 3: softmax-xent loss+grad epilogue ----------------------------------


def _composed_xent(logits, labels, valid, denom):
    import jax
    import jax.numpy as jnp

    logz = jax.nn.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.sum(jnp.where(valid, logz - ll, 0.0)) / denom


def test_softmax_xent_matches_composed_and_grad():
    import jax
    import jax.numpy as jnp

    from znicz_tpu.pallas_fused_block import fused_softmax_xent

    rng = np.random.default_rng(51)
    logits = _rand((6, 10), 51)
    labels = jnp.asarray(rng.integers(0, 10, 6).astype(np.int32))
    valid = jnp.arange(6) < 5                   # padded tail row masked
    denom = jnp.maximum(jnp.int32(5), 1)
    l_f = fused_softmax_xent(logits, labels, valid, denom)
    l_c = _composed_xent(logits, labels, valid, denom)
    np.testing.assert_allclose(float(l_f), float(l_c), rtol=1e-6)
    g = jax.grad(lambda lg: fused_softmax_xent(lg, labels, valid,
                                               denom))(logits)
    r = jax.grad(lambda lg: _composed_xent(lg, labels, valid,
                                           denom))(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-5,
                               atol=1e-7)
    # the masked row's gradient is exactly zero both ways
    assert float(np.abs(np.asarray(g)[5]).max()) == 0.0


def test_softmax_xent_finite_differences():
    import jax
    import jax.numpy as jnp

    from znicz_tpu.pallas_fused_block import fused_softmax_xent

    rng = np.random.default_rng(61)
    logits = _rand((3, 6), 61)
    labels = jnp.asarray(rng.integers(0, 6, 3).astype(np.int32))
    valid = jnp.arange(3) < 3
    denom = jnp.int32(3)

    def loss(lg):
        return fused_softmax_xent(lg, labels, valid, denom)

    g = jax.grad(loss)(logits)
    eps = 1e-3
    for idx in [(0, 0), (1, 3), (2, 5)]:
        e = jnp.zeros_like(logits).at[idx].set(eps)
        fd = (float(loss(logits + e)) - float(loss(logits - e))) / (2 * eps)
        assert abs(fd - float(g[idx])) <= 5e-2 * max(1e-3, abs(fd)), \
            (idx, fd, float(g[idx]))


# -- matcher / plan ------------------------------------------------------------


def _tail_workflow(max_epochs=2, minibatch_size=25):
    """conv_strict_relu -> max_pooling -> all2all_strict_relu -> dropout
    -> softmax: the AlexNet tail shape in miniature (15x15 textures; no
    LRN, so the conv matches the TAIL stage, not the block kernel).
    Sized for the tier-1 time budget — four e2e runs ride this shape."""
    from znicz_tpu import datasets
    from znicz_tpu.core import prng
    from znicz_tpu.loader.fullbatch import FullBatchLoader
    from znicz_tpu.standard_workflow import StandardWorkflow

    prng.reset(1013)

    class _Loader(FullBatchLoader):
        def load_data(self):
            data, labels = datasets.tinyimages(130, size=15)
            self.original_data.mem = data
            self.original_labels.mem = labels
            self.class_lengths = [0, 30, 100]
            super().load_data()

    gd = {"learning_rate": 0.02, "gradient_moment": 0.9}
    layers = [
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 8, "kx": 5, "ky": 5, "padding": (2, 2, 2, 2)},
         "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        {"type": "all2all_strict_relu", "->": {"output_sample_shape": 32},
         "<-": dict(gd)},
        {"type": "dropout", "->": {"dropout_ratio": 0.4}},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": dict(gd)},
    ]
    wf = StandardWorkflow(
        name="TailWF",
        loader=_Loader(name="loader", minibatch_size=minibatch_size),
        layers=layers, loss_function="softmax",
        decision_config={"max_epochs": max_epochs, "fail_iterations": 0})
    wf.initialize(device=None)
    return wf


def test_plan_fused_tail_matches_and_respects_flag():
    from znicz_tpu.pallas_fused_block import (plan_fused_blocks,
                                              plan_fused_tail)

    wf = _tail_workflow()
    assert plan_fused_tail(wf.forwards) == {}        # flag off -> no plan
    root.common.engine.fused_tail = True
    try:
        plan = plan_fused_tail(wf.forwards,
                               plan_fused_blocks(wf.forwards))
        assert sorted(plan) == [0, 2]
        assert plan[0].kind == "conv_bias_relu" and plan[0].span == 1
        fc = plan[2]
        assert (fc.kind, fc.span, fc.dropout_index) == ("fc_epilogue", 2, 3)
        assert fc.ratio == pytest.approx(0.4)
        # the softmax head is never an fc_epilogue (it is the loss head)
        assert 4 not in plan
    finally:
        root.common.engine.fused_tail = False


def test_plan_fused_tail_yields_to_conv_block_span():
    """With BOTH knobs on, an LRN'd conv block belongs to the single-pass
    block kernel; the tail matcher must not shadow its span."""
    from tests.test_fused_block_pallas import _tiny_alexstyle_workflow
    from znicz_tpu.pallas_fused_block import (plan_fused_blocks,
                                              plan_fused_tail)

    wf = _tiny_alexstyle_workflow()
    root.common.engine.fused_elementwise = True
    root.common.engine.fused_tail = True
    try:
        blocks = plan_fused_blocks(wf.forwards)
        assert list(blocks) == [0]
        tail = plan_fused_tail(wf.forwards, blocks)
        assert 0 not in tail                 # block kernel owns indices 0-2
        # but with the BLOCK knob off, the tail stage picks up the conv's
        # bias+relu (LRN/pool stay composed — same math either way)
        root.common.engine.fused_elementwise = False
        tail2 = plan_fused_tail(wf.forwards, plan_fused_blocks(wf.forwards))
        assert tail2[0].kind == "conv_bias_relu"
    finally:
        root.common.engine.fused_elementwise = False
        root.common.engine.fused_tail = False


# -- e2e trainer parity --------------------------------------------------------


def _run_fused(wf):
    from znicz_tpu.parallel.fused import FusedTrainer

    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    FusedTrainer(wf).run()
    return losses, {f.name: np.array(f.weights.map_read())
                    for f in wf.forwards if f.has_weights}


def test_trainer_fused_tail_matches_composed_path(tmp_path):
    """E2e FusedTrainer parity fused_tail on/off over 2 epochs: identical
    dropout masks (same fold_in key) and identical loss formula make the
    trajectories match to float-accumulation tolerance."""
    root.common.dirs.snapshots = str(tmp_path)
    l_off, w_off = _run_fused(_tail_workflow())
    root.common.engine.fused_tail = True
    try:
        l_on, w_on = _run_fused(_tail_workflow())
    finally:
        root.common.engine.fused_tail = False
    np.testing.assert_allclose(l_off, l_on, rtol=1e-4)
    assert l_on[-1] < l_on[0], l_on              # it actually trains
    for name in w_off:
        np.testing.assert_allclose(w_off[name], w_on[name], rtol=5e-3,
                                   atol=5e-5, err_msg=name)


def test_trainer_fused_tail_bf16_compute_dtype(tmp_path):
    """The new canonical ``compute_dtype`` knob drives the bf16 path
    through the fused tail: trajectory stays in band with the composed
    bf16 run, and the knob validates its spelling."""
    from znicz_tpu.parallel.fused import FusedTrainer

    root.common.dirs.snapshots = str(tmp_path)
    root.common.engine.compute_dtype = "bf16"    # the short alias
    try:
        wf = _tail_workflow()
        assert FusedTrainer(wf).compute_dtype == "bfloat16"
        l_off, _ = _run_fused(wf)                # same wf: build once
        root.common.engine.fused_tail = True
        try:
            l_on, _ = _run_fused(_tail_workflow())
        finally:
            root.common.engine.fused_tail = False
        np.testing.assert_allclose(l_off, l_on, rtol=5e-2)
        assert l_on[-1] < l_on[0], l_on
        # a bad spelling is refused at construction, not silently f32
        root.common.engine.compute_dtype = "float16"
        with pytest.raises(ValueError, match="compute_dtype"):
            FusedTrainer(wf)
    finally:
        root.common.engine.compute_dtype = None


def test_compute_dtype_bf16_mnist_convergence_band(tmp_path):
    """ISSUE 7 satellite: e2e f32 vs bf16-activations/f32-master parity
    band on the MNIST MLP (CPU, lean) under the canonical knob; the
    legacy ``precision`` spelling maps to the same path."""
    from znicz_tpu.parallel.fused import FusedTrainer

    root.common.dirs.snapshots = str(tmp_path)
    l_f32, _ = _run_fused(fresh_mnist(max_epochs=2))
    root.common.engine.compute_dtype = "bfloat16"
    try:
        wf = fresh_mnist(max_epochs=2)
        assert FusedTrainer(wf).compute_dtype == "bfloat16"
        l_bf16, _ = _run_fused(wf)               # same wf: build once
    finally:
        root.common.engine.compute_dtype = None
    np.testing.assert_allclose(l_f32, l_bf16, rtol=5e-2)
    assert l_bf16[-1] < l_bf16[0], l_bf16
    # legacy alias resolves identically (compute_dtype unset); reading
    # the dtype off a fresh trainer on the already-run wf is free
    root.common.engine.precision = "bfloat16"
    try:
        assert FusedTrainer(wf).compute_dtype == "bfloat16"
    finally:
        root.common.engine.precision = "float32"


# -- bf16 wire deltas vs the quarantine guard ----------------------------------


def test_bf16_nonfinite_delta_ships_raw_and_quarantines(tmp_path):
    """A non-finite gradient under the bf16 compute path must still be
    SEEN by the master's delta quarantine: the bf16 wire encoder ships
    non-finite deltas raw (nothing masked by quantization), and the
    server's quarantine flags them."""
    from znicz_tpu.core import prng
    from znicz_tpu.parallel import wire
    from znicz_tpu.server import Server

    enc = wire.DeltaEncoder("bfloat16")
    good = {"layer": {"weights": np.ones((4, 4), np.float32)}}
    bad = {"layer": {"weights": np.array([[np.inf, 1.0], [0.0, np.nan]],
                                         np.float32)}}
    qt_good = enc.encode(good)["layer"]["weights"]
    qt_bad = enc.encode(bad)["layer"]["weights"]
    assert isinstance(qt_good, wire.QuantizedTensor)
    assert qt_good.wire == "bfloat16"
    # non-finite: raw fallback (plain f32 array, no QuantizedTensor) —
    # the delta reaches the server's quarantine undisguised
    assert not isinstance(qt_bad, wire.QuantizedTensor)
    dec = np.asarray(qt_bad)
    assert not np.all(np.isfinite(dec))

    root.common.dirs.snapshots = str(tmp_path)
    prng.reset(1013)
    srv = Server(fresh_mnist(), segment_steps=2)
    assert srv._quarantine_reason({"layer": {"weights": dec}}) is not None
    assert srv._quarantine_reason(
        {"layer": {"weights": wire.dequantize(qt_good)}}) is None


# -- zero-recompile proof (staging + bf16) -------------------------------------


def test_staging_bf16_zero_recompiles(tmp_path):
    """Acceptance (ISSUE 7): the bf16 and async-staging paths add no jit
    cache entries after warmup — trace-counter + ``_cache_size()``
    cross-check, the serving layer's method on the training path."""
    from znicz_tpu.loader.streaming import HostArraySource
    from znicz_tpu.parallel.fused import FusedTrainer

    from tests.test_ingest import _build_stream_wf

    root.common.dirs.snapshots = str(tmp_path)
    root.common.engine.compute_dtype = "bf16"
    try:
        from znicz_tpu.core import prng

        prng.reset(1013)
        rng = np.random.default_rng(3)
        data = (rng.random((16, 6, 6)) * 255).astype(np.uint8)
        labels = (np.arange(16) % 2).astype(np.int32)
        wf = _build_stream_wf(HostArraySource(data, labels), max_epochs=2)
        trainer = FusedTrainer(wf)
        assert trainer.staging and trainer.compute_dtype == "bfloat16"
        trainer.run()
        assert trainer._stager is not None       # async staging engaged
        compiles0 = int(trainer._m_compiles.value)
        sizes0 = trainer.jit_cache_sizes()
        assert compiles0 > 0
        if sizes0:                               # jax exposes _cache_size
            assert sum(sizes0.values()) == compiles0, (sizes0, compiles0)
        # continue the SAME trainer for two more epochs: every dispatch
        # kind re-runs; nothing may re-trace
        wf.decision.complete.set(False)
        wf.decision.max_epochs = int(wf.decision.epoch_number) + 1 + 2
        trainer.run()
        assert int(trainer._m_compiles.value) == compiles0
        assert trainer.jit_cache_sizes() == sizes0
    finally:
        root.common.engine.compute_dtype = None


# -- XLA latency-hiding flags --------------------------------------------------


def test_xla_latency_hiding_flag_wiring():
    """``configure_xla_flags``: off by default, appends the published
    scheduler flags exactly once when the knob is on (scratch env — the
    launcher applies it to os.environ before the backend exists)."""
    from znicz_tpu.backends import (LATENCY_HIDING_XLA_FLAGS,
                                    configure_xla_flags)

    env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    assert configure_xla_flags(env) == ()        # knob off -> no-op
    root.common.engine.xla_latency_hiding = True
    try:
        added = configure_xla_flags(env)
        assert added == LATENCY_HIDING_XLA_FLAGS
        for f in LATENCY_HIDING_XLA_FLAGS:
            assert f in env["XLA_FLAGS"]
        # pre-existing flags survive; re-run is idempotent
        assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
        assert configure_xla_flags(env) == ()
        # an operator-set flag of the same NAME (different value) is
        # respected — no conflicting duplicate appended (last-wins parse
        # would silently override the operator)
        env2 = {"XLA_FLAGS": "--xla_tpu_host_transfer_overlap_limit=4"}
        added2 = configure_xla_flags(env2)
        assert all("host_transfer_overlap" not in f for f in added2)
        assert env2["XLA_FLAGS"].count(
            "--xla_tpu_host_transfer_overlap_limit") == 1
        assert "--xla_tpu_host_transfer_overlap_limit=4" in env2["XLA_FLAGS"]
    finally:
        root.common.engine.xla_latency_hiding = False
