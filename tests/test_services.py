"""L9 services: web status, forge, publishing, ensemble, misc units,
distributable protocol, resizable FC, interaction shell."""

import json
import os
import urllib.request

import numpy as np

from znicz_tpu.core.config import root
from znicz_tpu.memory import Array


def _tiny_trained_mnist(tmp_path, epochs=1):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 120
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = epochs
    root.common.dirs.snapshots = str(tmp_path)
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    wf.run()
    return wf


def test_web_status(tmp_path):
    from znicz_tpu.web_status import WebStatus

    wf = _tiny_trained_mnist(tmp_path)
    status = WebStatus(port=0).start()
    try:
        status.register(wf)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/status.json") as r:
            snap = json.load(r)
        assert snap["workflows"][0]["name"] == "MnistWorkflow"
        assert snap["workflows"][0]["complete"] is True
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/") as r:
            page = r.read().decode()
        assert "MnistWorkflow" in page
    finally:
        status.stop()


def test_forge_roundtrip(tmp_path):
    from znicz_tpu import snapshotter
    from znicz_tpu.forge import Forge

    wf = _tiny_trained_mnist(tmp_path)
    forge = Forge(registry=str(tmp_path / "registry"))
    forge.upload(wf, "mnist-mlp", metadata={"acc": 0.9})
    entries = forge.list()
    assert entries[0]["name"] == "mnist-mlp"
    snap = forge.download("mnist-mlp")
    w0 = np.array(wf.forwards[0].weights.map_read())
    np.testing.assert_allclose(snap["units"]["fwd0"]["weights"], w0)
    forge.delete("mnist-mlp")
    assert forge.list() == []


def test_publishing(tmp_path):
    from znicz_tpu.publishing import publish

    wf = _tiny_trained_mnist(tmp_path)
    path = publish(wf, backend="markdown", directory=str(tmp_path / "rep"))
    text = open(path).read()
    assert "Training report" in text
    assert "best_metric" in text
    path2 = publish(wf, backend="html", directory=str(tmp_path / "rep"))
    assert open(path2).read().startswith("<html>")


def test_ensemble(tmp_path):
    from znicz_tpu.ensemble import EnsembleEvaluator, EnsembleTrainer
    from znicz_tpu.samples import mnist

    root.mnist.loader.n_train = 120
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 1
    root.common.dirs.snapshots = str(tmp_path)

    def factory(seed):
        wf = mnist.MnistWorkflow()
        wf.initialize(device=None)
        wf.run()
        return wf

    trainer = EnsembleTrainer(factory, n_models=2).run()
    assert len(trainer.members) == 2
    # member weights differ (different seeds)
    w0 = np.array(trainer.members[0].forwards[0].weights.map_read())
    w1 = np.array(trainer.members[1].forwards[0].weights.map_read())
    assert not np.allclose(w0, w1)

    from znicz_tpu import datasets
    data, labels = datasets.digits(20, stream="dataset.ens")
    ev = EnsembleEvaluator(trainer.members)
    probs = ev.predict_proba(data.reshape(20, -1))
    assert probs.shape == (20, 10)
    np.testing.assert_allclose(probs.sum(-1), 1.0, rtol=1e-4)
    assert ev.n_err(data.reshape(20, -1), labels) <= 20


def test_distributable_protocol():
    from znicz_tpu.all2all import All2All

    fwd = All2All(name="distfwd", output_sample_shape=(3,))
    fwd.input = Array(np.ones((2, 4), np.float32))
    fwd.initialize(device=None)
    payload = fwd.generate_data_for_slave()
    assert set(payload) == {"weights", "bias"}
    fwd2 = All2All(name="distfwd2", output_sample_shape=(3,))
    fwd2.input = Array(np.ones((2, 4), np.float32))
    fwd2.initialize(device=None)
    fwd2.apply_data_from_master(payload)
    np.testing.assert_allclose(np.array(fwd2.weights.map_read()),
                               payload["weights"])
    up = fwd2.generate_data_for_master()
    fwd.apply_data_from_slave(up)
    np.testing.assert_allclose(np.array(fwd.weights.map_read()),
                               up["weights"])


def test_resizable_all2all():
    from znicz_tpu.resizable_all2all import ResizableAll2All

    fwd = ResizableAll2All(name="rsz", output_sample_shape=(4,))
    fwd.input = Array(np.ones((2, 5), np.float32))
    fwd.initialize(device=None)
    fwd.run()
    w_before = np.array(fwd.weights.map_read()).copy()
    fwd.resize(7)
    assert fwd.weights.shape == (7, 5)
    np.testing.assert_allclose(np.array(fwd.weights.map_read())[:4],
                               w_before)
    fwd.run()
    assert tuple(fwd.output.shape) == (2, 7)
    fwd.resize(3)
    fwd.run()
    assert tuple(fwd.output.shape) == (2, 3)


def test_zero_filler_and_rollback():
    from znicz_tpu.all2all import All2All
    from znicz_tpu.misc_units import NNRollback, ZeroFiller

    fwd = All2All(name="zf_fwd", output_sample_shape=(3,))
    fwd.input = Array(np.ones((2, 4), np.float32))
    fwd.initialize(device=None)
    mask = np.ones((3, 4), bool)
    mask[0, :] = False
    zf = ZeroFiller(name="zf")
    zf.add_mask(fwd, mask)
    zf.run()
    assert np.all(np.array(fwd.weights.map_read())[0] == 0)

    rb = NNRollback(name="rb", rollback_factor=2.0)
    rb.watch(fwd)
    rb.loss = 1.0
    rb.run()                                  # records best
    good = np.array(fwd.weights.map_read()).copy()
    fwd.weights.map_write()[...] = 99.0
    rb.loss = 10.0                            # diverged
    rb.run()
    np.testing.assert_allclose(np.array(fwd.weights.map_read()), good)
    assert rb.rollbacks == 1


def test_mean_disp_unit():
    from znicz_tpu.misc_units import MeanDispNormalizerUnit

    rng = np.random.default_rng(7)
    x = rng.normal(3.0, 2.0, size=(10, 6)).astype(np.float32)
    unit = MeanDispNormalizerUnit(name="mdn")
    unit.input = Array(x)
    unit.mean.mem = x.mean(0)
    unit.disp.mem = (x.max(0) - x.min(0))
    unit.initialize(device=None)
    unit.run()
    got = np.array(unit.output.map_read())
    want = (x - x.mean(0)) / (x.max(0) - x.min(0))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_shell_unit_noop():
    from znicz_tpu.interaction import Shell

    sh = Shell(name="shell", interactive=False)
    sh.run()
    assert sh.invocations == 1

def test_forge_rejects_escaping_names(tmp_path):
    from znicz_tpu.forge import Forge

    forge = Forge(registry=str(tmp_path / "reg2"))
    import pytest as _pytest
    with _pytest.raises(ValueError):
        forge._pkg_dir("..")
    with _pytest.raises(ValueError):
        forge._pkg_dir(".")


def test_gd_distributable_ships_velocities():
    from znicz_tpu.all2all import All2All
    from znicz_tpu.gd import GradientDescent

    fwd = All2All(name="gdist_fwd", output_sample_shape=(2,))
    fwd.input = Array(np.ones((2, 3), np.float32))
    fwd.initialize(device=None)
    gd = GradientDescent(name="gdist", forward=fwd, learning_rate=0.1,
                         gradient_moment=0.9, need_err_input=False)
    gd.err_output = Array(np.ones((2, 2), np.float32))
    gd.initialize(device=None)
    fwd.run(); gd.run()
    payload = gd.generate_data_for_master()
    assert set(payload) == {"weights", "bias"}
    assert np.any(payload["weights"] != 0)
    gd2 = GradientDescent(name="gdist2", forward=fwd, gradient_moment=0.9)
    gd2.err_output = gd.err_output
    gd2.initialize(device=None)
    gd2.apply_data_from_master(payload)
    np.testing.assert_allclose(
        np.array(gd2._velocities["weights"].map_read()),
        payload["weights"])


def test_mean_disp_unit_refit_not_stale():
    from znicz_tpu.misc_units import MeanDispNormalizerUnit

    x = np.ones((4, 3), np.float32)
    unit = MeanDispNormalizerUnit(name="mdn2")
    unit.input = Array(x)
    unit.mean.mem = np.zeros(3, np.float32)
    unit.disp.mem = np.ones(3, np.float32)
    unit.initialize(device=None)
    unit.run()
    np.testing.assert_allclose(np.array(unit.output.map_read()), x)
    unit.mean.mem = np.ones(3, np.float32)     # refit
    unit.run()
    np.testing.assert_allclose(np.array(unit.output.map_read()),
                               np.zeros_like(x))


def test_resizable_reallocates_gd_velocities():
    from znicz_tpu.core.workflow import Workflow
    from znicz_tpu.gd import GradientDescent
    from znicz_tpu.resizable_all2all import ResizableAll2All

    wf = Workflow(name="rszwf")
    fwd = ResizableAll2All(wf, name="rszv", output_sample_shape=(4,))
    fwd.input = Array(np.ones((2, 5), np.float32))
    fwd.initialize(device=None)
    gd = GradientDescent(wf, name="rszv_gd", forward=fwd,
                         gradient_moment=0.9, need_err_input=False)
    gd.err_output = Array(np.ones((2, 4), np.float32))
    gd.initialize(device=None)
    fwd.run(); gd.run()
    fwd.resize(7)
    assert gd._velocities["weights"].shape == (7, 5)
    gd.err_output = Array(np.ones((2, 7), np.float32))
    fwd.run(); gd.run()                        # no broadcast crash
    assert np.array(fwd.weights.map_read()).shape == (7, 5)


def test_forge_remote_roundtrip(tmp_path):
    """VERDICT r2 missing #2: publish over HTTP from one registry, fetch
    into another process-side client, restore and RUN the fetched model."""
    import pytest

    from znicz_tpu import snapshotter
    from znicz_tpu.forge import ForgeServer, RemoteForge

    wf = _tiny_trained_mnist(tmp_path)
    server = ForgeServer(registry=str(tmp_path / "server_reg"),
                         port=0).start()
    try:
        remote = RemoteForge(f"http://127.0.0.1:{server.port}")
        remote.upload(wf, "mnist-mlp", metadata={"acc": 0.9})
        entries = remote.list()
        assert [e["name"] for e in entries] == ["mnist-mlp"]
        assert remote.manifest("mnist-mlp")["metadata"]["acc"] == 0.9

        snap = remote.download("mnist-mlp")
        w0 = np.array(wf.forwards[0].weights.map_read())
        np.testing.assert_allclose(snap["units"]["fwd0"]["weights"], w0)

        # restore into a FRESH workflow replica and run it further
        from znicz_tpu.core import prng
        from znicz_tpu.samples import mnist

        prng.reset(1013)
        root.mnist.decision.max_epochs = 2
        wf2 = mnist.MnistWorkflow()
        wf2.initialize(device=None)
        snapshotter.restore(wf2, snap)
        np.testing.assert_allclose(
            np.array(wf2.forwards[0].weights.map_read()), w0)
        wf2.run()                       # the fetched model trains on
        assert bool(wf2.decision.complete)

        remote.delete("mnist-mlp")
        assert remote.list() == []
    finally:
        server.stop()

    with pytest.raises(ValueError, match="non-loopback"):
        RemoteForge("http://evil.example.com:80")
    RemoteForge("http://evil.example.com:80", allow_remote=True)  # opt-in


def test_publishing_pdf(tmp_path):
    """PDF backend renders a valid, non-empty multi-page PDF (VERDICT r2
    item 9; confluence is a documented drop — needs a server)."""
    from znicz_tpu.publishing import publish

    # give the report a plot page too
    plots = tmp_path / "plots"
    plots.mkdir()
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots()
    ax.plot([0, 1], [1, 0])
    fig.savefig(plots / "err.png")
    plt.close(fig)
    root.common.dirs.plots = str(plots)

    wf = _tiny_trained_mnist(tmp_path)
    path = publish(wf, backend="pdf", directory=str(tmp_path / "rep"))
    assert path.endswith(".pdf")
    blob = open(path, "rb").read()
    assert blob.startswith(b"%PDF-") and blob.rstrip().endswith(b"%%EOF")
    assert len(blob) > 2000
    assert blob.count(b"/Type /Page") >= 3      # title + timing + plot


def test_launcher_fused_flag(tmp_path, monkeypatch):
    """--fused trains the sample through the FusedTrainer fast path."""
    from znicz_tpu import launcher
    from znicz_tpu.core import prng

    monkeypatch.chdir(tmp_path)
    prng.reset(1013)
    try:
        rc = launcher.main([
            "mnist", "root.mnist.loader.n_train=120",
            "root.mnist.loader.n_valid=60",
            "root.mnist.loader.minibatch_size=60",
            "root.mnist.decision.max_epochs=2",
            f"root.common.dirs.snapshots={tmp_path}", "--fused"])
        assert rc == 0
        assert bool(root.common.engine.get("fused")) is True
        # (that the flag actually routes through FusedTrainer is proven
        # directly by test_engine_train_fused_and_fallback below)
    finally:
        root.common.engine.fused = False


def test_engine_train_fused_and_fallback(tmp_path):
    """engine.train: fused flag routes GD workflows through FusedTrainer
    (fused_stats appear); non-GD workflows (Kohonen) fall back to the
    unit engine without error."""
    from znicz_tpu import engine
    from znicz_tpu.core import prng
    from znicz_tpu.samples import kohonen, mnist

    root.common.dirs.snapshots = str(tmp_path)
    prng.reset(1013)
    root.mnist.loader.n_train = 120
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 2
    root.common.engine.fused = True
    try:
        wf = mnist.MnistWorkflow()
        wf.initialize(device=None)
        engine.train(wf)
        assert wf.fused_stats["train_steps"] > 0     # fused path ran
        assert bool(wf.decision.complete)

        prng.reset(1013)
        root.kohonen.decision.max_epochs = 2
        kwf = kohonen.KohonenWorkflow()
        kwf.initialize(device=None)
        engine.train(kwf)                            # falls back cleanly
        assert getattr(kwf, "fused_stats", None) is None
    finally:
        root.common.engine.fused = False


def test_snapshotter_orbax_format_roundtrip(tmp_path):
    """TPU-native checkpoint backend (SURVEY §3.5 rebuild note): weights /
    velocities via orbax-tensorstore, metadata as JSON — restore into a
    fresh replica matches the pickle path bit-for-bit and training
    continues."""
    from znicz_tpu import snapshotter
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist
    from znicz_tpu.snapshotter import Snapshotter

    wf = _tiny_trained_mnist(tmp_path, epochs=2)
    snap_unit = wf.snapshotter
    snap_unit.format = "orbax"
    path = snap_unit.save("orbax_test")
    assert path.endswith(".orbax") and os.path.isdir(path)
    assert os.path.exists(os.path.join(path, "meta.json"))

    snap = Snapshotter.load(path)
    w0 = np.array(wf.forwards[0].weights.map_read())
    np.testing.assert_array_equal(snap["units"]["fwd0"]["weights"], w0)
    assert snap["epoch"] == 1

    prng.reset(1013)
    root.mnist.decision.max_epochs = 3
    wf2 = mnist.MnistWorkflow()
    wf2.initialize(device=None)
    snapshotter.restore(wf2, snap)
    np.testing.assert_array_equal(
        np.array(wf2.forwards[0].weights.map_read()), w0)
    wf2.run()                           # continues training
    assert bool(wf2.decision.complete)


def test_orbax_meta_roundtrips_numpy_state(tmp_path):
    """Normalizer-style numpy arrays in the metadata sidecar round-trip
    exactly (review finding: default=repr silently corrupted them)."""
    from znicz_tpu.snapshotter import _load_orbax, _save_orbax

    mean = np.linspace(0, 1, 2000).astype(np.float32)   # > print threshold
    snap = {"units": {"f": {"w": np.ones((2, 2), np.float32)}},
            "velocities": {},
            "loader": {"epoch_number": 2,
                       "normalizer": {"kind": "mean_disp", "mean": mean,
                                      "disp": mean * 2 + 1}},
            "decision": {"best_metric": 0.5, "best_epoch": 1, "fails": 0},
            "prng": {}, "epoch": 2, "metric": 0.5}
    path = str(tmp_path / "s.orbax")
    _save_orbax(path, snap)
    back = _load_orbax(path)
    got = back["loader"]["normalizer"]
    assert got["kind"] == "mean_disp"
    np.testing.assert_array_equal(got["mean"], mean)
    np.testing.assert_array_equal(got["disp"], mean * 2 + 1)
    assert back["loader"]["epoch_number"] == 2


def test_publish_includes_fused_stats(tmp_path):
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.publishing import publish
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 120
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 1
    root.common.dirs.snapshots = str(tmp_path)
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    FusedTrainer(wf).run()
    path = publish(wf, backend="markdown", directory=str(tmp_path / "rep"))
    text = open(path).read()
    assert "fused_img_per_sec" in text and "fused_train_steps" in text


def test_engine_master_mode_rejects_nondistributable(tmp_path):
    from znicz_tpu import engine
    from znicz_tpu.core import prng
    from znicz_tpu.samples import kohonen

    import pytest as _pytest

    prng.reset(1013)
    root.kohonen.decision.max_epochs = 1
    root.common.dirs.snapshots = str(tmp_path)
    wf = kohonen.KohonenWorkflow()
    wf.initialize(device=None)
    root.common.engine.mode = "master"
    try:
        with _pytest.raises(ValueError, match="--master"):
            engine.train(wf)
    finally:
        root.common.engine.mode = ""
