"""Single-pass fused conv-block Pallas kernel
(znicz_tpu/pallas_fused_block.py): forward bit-parity vs the composed
bias+StrictRELU+LRN+maxpool ops, backward vs the composed VJP and vs
finite differences (interpreter mode on the CPU test platform), matcher /
geometry-fallback behavior, and end-to-end FusedTrainer parity with the
``fused_elementwise`` flag on vs off.  Also covers this round's satellite
hardening: the dedicated fused-slave staging refusal type, the server's
segment-metrics length validation, and Array.host_dirty."""

import numpy as np
import pytest

from znicz_tpu.core.config import root

N, ALPHA, BETA, K = 5, 1e-4, 0.75, 2.0
POOL = (3, 3, 2, 2)


def _composed(x, b, n=N, alpha=ALPHA, beta=BETA, k=K, pool=POOL):
    """The composed oracle: relu(x+b) -> LRN (shifted-slices oracle, same
    as tests/test_lrn_pallas.py) -> exactly-tiling overlapping maxpool."""
    import jax.numpy as jnp
    from jax import lax

    ky, kx, sy, sx = pool
    r = jnp.maximum(x + b, 0.0)
    half = n // 2
    padded = jnp.pad(jnp.square(r), [(0, 0)] * (r.ndim - 1) + [(half, half)])
    acc = jnp.zeros_like(r)
    for j in range(n):
        acc = acc + padded[..., j:j + r.shape[-1]]
    y = r / jnp.power(k + alpha * acc, beta)
    return lax.reduce_window(y, x.dtype.type(-np.inf), lax.max,
                             (1, ky, kx, 1), (1, sy, sx, 1), "VALID")


def _rand(shape, seed, scale=1.0):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=shape).astype(np.float32) * scale)


def test_fused_block_forward_matches_composed():
    import jax.numpy as jnp

    from znicz_tpu.pallas_fused_block import fused_block

    x = _rand((2, 9, 9, 32), 3, 2.0)
    b = _rand((32,), 4, 0.1)
    out = fused_block(x, b, N, ALPHA, BETA, K, POOL)
    ref = _composed(x, b)
    assert out.shape == ref.shape == (2, 4, 4, 32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    # a second geometry (non-overlapping 2x2, 96 channels like conv1)
    x2 = _rand((1, 8, 8, 96), 5)
    b2 = _rand((96,), 6, 0.1)
    out2 = fused_block(x2, b2, N, ALPHA, BETA, K, (2, 2, 2, 2))
    ref2 = _composed(x2, b2, pool=(2, 2, 2, 2))
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               rtol=1e-5, atol=1e-6)


def test_fused_block_forward_bf16_within_tolerance():
    import jax.numpy as jnp

    from znicz_tpu.pallas_fused_block import fused_block

    x = _rand((2, 9, 9, 32), 7).astype(jnp.bfloat16)
    b = _rand((32,), 8, 0.1).astype(jnp.bfloat16)
    out = fused_block(x, b, N, ALPHA, BETA, K, POOL)
    assert out.dtype == jnp.bfloat16
    ref = _composed(x.astype(jnp.float32), b.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out.astype(jnp.float32)),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_fused_block_grad_matches_composed_vjp():
    import jax
    import jax.numpy as jnp

    from znicz_tpu.pallas_fused_block import fused_block

    x = _rand((2, 9, 9, 32), 11, 2.0)
    b = _rand((32,), 12, 0.1)
    cot = _rand((2, 4, 4, 32), 13)

    gx, gb = jax.grad(
        lambda xx, bb: jnp.sum(
            fused_block(xx, bb, N, ALPHA, BETA, K, POOL) * cot),
        argnums=(0, 1))(x, b)
    rx, rb = jax.grad(
        lambda xx, bb: jnp.sum(_composed(xx, bb) * cot),
        argnums=(0, 1))(x, b)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               rtol=2e-4, atol=2e-6)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb),
                               rtol=2e-4, atol=1e-5)


def test_fused_block_grad_finite_differences():
    import jax
    import jax.numpy as jnp

    from znicz_tpu.pallas_fused_block import fused_block

    # keep pre-activations away from the ReLU kink so the FD probe is on
    # a smooth branch (the kink itself is measure-zero and covered by the
    # composed-vjp parity above)
    x = _rand((1, 5, 5, 8), 21)
    x = jnp.sign(x) * (jnp.abs(x) + 0.3)
    b = _rand((8,), 22, 0.05)
    cot = _rand((1, 2, 2, 8), 23)

    def loss(xx, bb):
        return jnp.sum(fused_block(xx, bb, N, ALPHA, BETA, K, POOL) * cot)

    gx, gb = jax.grad(loss, argnums=(0, 1))(x, b)
    eps = 1e-3
    for idx in [(0, 0, 0, 0), (0, 2, 3, 5), (0, 4, 4, 7), (0, 1, 2, 2)]:
        e = jnp.zeros_like(x).at[idx].set(eps)
        fd = (float(loss(x + e, b)) - float(loss(x - e, b))) / (2 * eps)
        assert abs(fd - float(gx[idx])) <= 5e-2 * max(1.0, abs(fd)), \
            (idx, fd, float(gx[idx]))
    for ci in (0, 3, 7):
        e = jnp.zeros_like(b).at[ci].set(eps)
        fd = (float(loss(x, b + e)) - float(loss(x, b - e))) / (2 * eps)
        assert abs(fd - float(gb[ci])) <= 5e-2 * max(1.0, abs(fd)), \
            (ci, fd, float(gb[ci]))


def test_fused_block_rejects_non_tiling_pool():
    from znicz_tpu.pallas_fused_block import fused_block

    x = _rand((1, 6, 6, 8), 31)        # (6-3) % 2 != 0: partial windows
    b = _rand((8,), 32)
    with pytest.raises(AssertionError, match="tile"):
        fused_block(x, b, N, ALPHA, BETA, K, POOL)


# -- matcher / trainer routing ------------------------------------------------


def _tiny_alexstyle_workflow(minibatch_size=50, max_epochs=2,
                             pool_kwargs=None):
    """conv_strict_relu -> norm -> max_pooling -> softmax on a 19x19
    procedural texture set: 19 = 2*8 + 3, so the 3x3/s2 overlapping pool
    tiles the plane exactly (the conv1/conv2 condition)."""
    from znicz_tpu import datasets
    from znicz_tpu.core import prng
    from znicz_tpu.loader.fullbatch import FullBatchLoader
    from znicz_tpu.standard_workflow import StandardWorkflow

    prng.reset(1013)

    class _Loader(FullBatchLoader):
        def load_data(self):
            data, labels = datasets.tinyimages(260, size=19)
            self.original_data.mem = data
            self.original_labels.mem = labels
            self.class_lengths = [0, 60, 200]
            super().load_data()

    gd = {"learning_rate": 0.02, "gradient_moment": 0.9}
    layers = [
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 16, "kx": 5, "ky": 5, "padding": (2, 2, 2, 2)},
         "<-": dict(gd)},
        {"type": "norm"},
        {"type": "max_pooling",
         "->": pool_kwargs or {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        {"type": "softmax", "->": {"output_sample_shape": 10}, "<-": dict(gd)},
    ]
    wf = StandardWorkflow(
        name="TinyAlexStyle",
        loader=_Loader(name="loader", minibatch_size=minibatch_size),
        layers=layers, loss_function="softmax",
        decision_config={"max_epochs": max_epochs, "fail_iterations": 0})
    wf.initialize(device=None)
    return wf


def test_plan_matches_conv_block_and_respects_flag():
    from znicz_tpu.pallas_fused_block import plan_fused_blocks

    wf = _tiny_alexstyle_workflow()
    assert plan_fused_blocks(wf.forwards) == {}      # flag off -> no plan
    root.common.engine.fused_elementwise = True
    try:
        plan = plan_fused_blocks(wf.forwards)
        assert list(plan) == [0]
        spec = plan[0]
        assert (spec.span, spec.n, spec.pool) == (3, 5, (3, 3, 2, 2))
        # the LRN-formulation experiment knobs keep their re-runs pure
        root.common.engine.lrn_autodiff = True
        try:
            assert plan_fused_blocks(wf.forwards) == {}
        finally:
            root.common.engine.lrn_autodiff = False
    finally:
        root.common.engine.fused_elementwise = False


def test_plan_falls_back_on_partial_edge_windows():
    """A pool whose windows do NOT tile the plane (non-overlapping 2x2 on
    19x19 -> partial edge column/row) must not match; the composed ops
    keep running and the workflow still trains."""
    from znicz_tpu.pallas_fused_block import plan_fused_blocks

    wf = _tiny_alexstyle_workflow(
        pool_kwargs={"kx": 2, "ky": 2})     # sliding=(2,2); 19 % 2 != 0
    assert not wf.forwards[2].exact_tiling()
    root.common.engine.fused_elementwise = True
    try:
        assert plan_fused_blocks(wf.forwards) == {}
    finally:
        root.common.engine.fused_elementwise = False


def _run_fused(wf):
    from znicz_tpu.parallel.fused import FusedTrainer

    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    FusedTrainer(wf).run()
    return losses, {f.name: np.array(f.weights.map_read())
                    for f in wf.forwards if f.has_weights}


def test_trainer_fused_block_matches_composed_path(tmp_path):
    """End-to-end FusedTrainer parity: fused_elementwise on vs off over 2
    epochs — same losses and final weights within float-accumulation
    tolerance (the kernel's tie semantics differ only where the ReLU mask
    zeroes the gradient anyway; see pallas_fused_block docstring)."""
    root.common.dirs.snapshots = str(tmp_path)
    l_off, w_off = _run_fused(_tiny_alexstyle_workflow())
    root.common.engine.fused_elementwise = True
    try:
        l_on, w_on = _run_fused(_tiny_alexstyle_workflow())
    finally:
        root.common.engine.fused_elementwise = False
    np.testing.assert_allclose(l_off, l_on, rtol=1e-3)
    assert l_on[-1] < l_on[0], l_on                  # it actually trains
    for name in w_off:
        np.testing.assert_allclose(w_off[name], w_on[name], rtol=5e-3,
                                   atol=5e-5, err_msg=name)


def test_trainer_fused_block_bf16_trains(tmp_path):
    """Mixed precision through the kernel: bf16 activations in, bf16 out,
    f32 internal math — the loss trajectory stays in band with the
    composed bf16 path."""
    root.common.dirs.snapshots = str(tmp_path)
    root.common.engine.precision = "bfloat16"
    try:
        l_off, _ = _run_fused(_tiny_alexstyle_workflow())
        root.common.engine.fused_elementwise = True
        try:
            l_on, _ = _run_fused(_tiny_alexstyle_workflow())
        finally:
            root.common.engine.fused_elementwise = False
    finally:
        root.common.engine.precision = "float32"
    np.testing.assert_allclose(l_off, l_on, rtol=5e-2)
    assert l_on[-1] < l_on[0], l_on


# -- satellite hardening ------------------------------------------------------


def test_staging_refusal_is_dedicated_exception_type():
    """The fused-slave host-staged-loader refusal is a dedicated
    FusedUnsupportedError subclass, so engine.train's slave fallback
    catches exactly the known refusals and real ValueErrors propagate."""
    from znicz_tpu.parallel.fused import (FusedStagingUnsupportedError,
                                          FusedUnsupportedError)

    assert issubclass(FusedStagingUnsupportedError, FusedUnsupportedError)
    assert issubclass(FusedStagingUnsupportedError, ValueError)


def test_server_refuses_short_segment_metrics(tmp_path):
    """A segment update whose metrics list is shorter than the job's
    minibatch list is refused (no decision feed, no deltas) and the job is
    re-queued — zip() must not silently truncate (server.py satellite)."""
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist
    from znicz_tpu.server import Server

    prng.reset(1013)
    root.common.dirs.snapshots = str(tmp_path)
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 3
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    srv = Server(wf, segment_steps=3)
    srv.registered.add("s1")

    def next_job():
        while True:
            r = srv._handle({"cmd": "job", "id": "s1"})
            if not r.get("wait"):
                return r

    def next_segment_job():
        """Drain eval singletons / flat train tails (well-formed replies)
        until the server issues a segment job."""
        for _ in range(64):
            r = next_job()
            if "minibatches" in r["job"]:
                return r
            srv._handle({"cmd": "update", "id": "s1",
                         "job_id": r["job_id"], "deltas": None,
                         "metrics": {"loss": 1.0, "n_err": 0}})
        raise AssertionError("no segment job issued")

    rep = next_segment_job()
    job = rep["job"]
    srv.jobs_done = 0                    # count only the segment exchange
    assert len(job["minibatches"]) > 1
    n_mb = len(job["minibatches"])
    before = np.array(wf.forwards[0].weights.map_read()).copy()
    bad = srv._handle({"cmd": "update", "id": "s1", "job_id": rep["job_id"],
                       "deltas": {wf.forwards[0].name: {
                           "weights": np.ones_like(before)}},
                       "metrics": [{"loss": 1.0}] * (n_mb - 1)})
    assert bad["ok"] is False and "metrics length" in bad["error"]
    assert srv.bad_updates == 1
    assert srv.jobs_done == 0
    # the refused update applied nothing and the job went back to pending
    np.testing.assert_array_equal(
        before, np.array(wf.forwards[0].weights.map_read()))
    assert any(j.get("kind") == "segment" for j in srv._pending)
    # a well-formed reply for the re-queued job is accepted
    rep2 = srv._handle({"cmd": "job", "id": "s1"})
    ok = srv._handle({"cmd": "update", "id": "s1", "job_id": rep2["job_id"],
                      "deltas": None,
                      "metrics": [{"loss": 1.0}] * n_mb})
    assert ok["ok"] is True and srv.jobs_done == 1
    # a deterministically-broken slave must NOT livelock: after
    # MAX_BAD_REPLIES refusals of the SAME job it is dropped, not requeued
    rep3 = next_segment_job()
    job3 = rep3["job"]
    for attempt in range(srv.MAX_BAD_REPLIES):
        bad = srv._handle({"cmd": "update", "id": "s1",
                           "job_id": rep3["job_id"], "deltas": None,
                           "metrics": []})
        assert bad["ok"] is False
        if attempt < srv.MAX_BAD_REPLIES - 1:
            rep3 = next_job()
            assert rep3["job"] is job3       # same requeued job
    assert not srv._pending                  # dropped, not requeued


def test_array_host_dirty_tracks_map_state():
    from znicz_tpu.memory import Array

    a = Array(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert a.host_dirty                      # fresh host data, no device
    _ = a.devmem
    assert not a.host_dirty                  # synced
    a.map_write()[0, 0] = 7.0
    assert a.host_dirty                      # host newer than device
    _ = a.devmem
    assert not a.host_dirty


def test_op_value_refuses_stale_cross_host_shard():
    """_op_value must raise, not silently hand out a stale sharded device
    buffer, when the host copy is newer (fused.py satellite).  The
    cross-host condition is simulated via the same attributes
    Array.cross_host_sharded reads."""
    from znicz_tpu.memory import Array
    from znicz_tpu.parallel.fused import FusedTrainer

    class _FakeGlobal:
        is_fully_addressable = False
        is_fully_replicated = False

        def is_deleted(self):
            return False

    arr = Array(np.zeros((2, 2), np.float32))
    arr._devmem = _FakeGlobal()              # pretend: sharded global array
    arr._state = 0                           # synced -> passes through
    trainer = FusedTrainer.__new__(FusedTrainer)
    trainer.mesh = object()                  # non-None mesh

    import jax

    if jax.process_count() > 1:              # single-process test only
        pytest.skip("single-controller test")
    orig = jax.process_count
    jax.process_count = lambda: 2
    try:
        assert trainer._op_value(arr) is arr._devmem
        arr._state = 1                       # _HOST_DIRTY
        with pytest.raises(RuntimeError, match="NEWER host copy"):
            trainer._op_value(arr)
    finally:
        jax.process_count = orig
