"""Autoregressive generation serving (ISSUE 16, paged in ISSUE 19):
the decode-attention NaN guard, paged-vs-contiguous bit-exactness,
page-pool refcount accounting (leak audit), chunked-prefill parity
with the full forward, prefix-cache hit bit-exactness + copy-on-write
divergence, the continuous-batching scheduler (mid-batch release,
determinism, resend dedup, page-pressure stalls), on-device-vs-host
sampling parity, the e2e ``generate`` service (streaming, refusals,
neighbor invisibility, repeat-stream jit-cache hygiene), the web panel
generation rows, and a chaos soak (slow)."""

import json
import time
import urllib.request

import numpy as np
import pytest

from znicz_tpu.core.config import root

VOCAB = 32


def _charlm_wf(seq_len=32):
    from znicz_tpu.core import prng
    from znicz_tpu.samples.charlm import CharLMWorkflow

    prng.reset(1013)
    root.charlm.loader.update({"n_train": 64, "n_valid": 16, "n_test": 0,
                               "seq_len": seq_len, "minibatch_size": 16})
    root.charlm.model.update({"vocab": VOCAB, "embed": 32, "heads": 2,
                              "ffn": 64})
    wf = CharLMWorkflow()
    wf.initialize(device=None)
    return wf


def _gen_runner(wf, page_size=8, num_pages=16, slots=2, prefill_chunk=8,
                prefix_cache=True):
    from znicz_tpu.serving.model import ModelRunner

    runner = ModelRunner(wf)
    return runner.enable_generation(page_size=page_size,
                                    num_pages=num_pages, slots=slots,
                                    prefill_chunk=prefill_chunk,
                                    prefix_cache=prefix_cache)


def _greedy(gen, prompt, n_new, pages=None):
    """Drive one request by hand through the paged runner: chunked
    prefill + greedy decode.  Returns (tokens, page list)."""
    prompt = np.asarray(prompt).reshape(-1)
    ps, c = gen.page_size, gen.prefill_chunk
    pages = [] if pages is None else pages
    t0 = len(pages) * ps if pages else 0
    t0 = min(t0, len(prompt) - 1)
    tok = None
    while t0 < len(prompt):
        n = min(c, len(prompt) - t0)
        need = -(-(t0 + n) // ps)
        while len(pages) < need:
            pages.append(gen.alloc_page())
        x = np.zeros((1, c), gen.runner.dtype)
        x[0, :n] = prompt[t0:t0 + n]
        tok, _, _, _ = gen.prefill(x, [t0], [n], [pages],
                                   [0.0], [0], [0])
        t0 += n
    toks = [int(tok[0])]
    t = len(prompt)
    for _ in range(n_new - 1):
        if t % ps == 0:
            pages.append(gen.alloc_page())
        tok, _, _, _ = gen.decode([pages], [toks[-1]], [t],
                                  [0.0], [0], [0])
        toks.append(int(tok[0]))
        t += 1
    return toks, pages


@pytest.fixture()
def _generate_config():
    """Enable the generation plane for a server test, restore after."""
    root.common.serving.seq.rungs = [8, 32]
    root.common.serving.generate.update({
        "enabled": True, "page_size": 8, "slots": 4})
    yield
    root.common.serving.generate.enabled = False
    root.common.serving.seq.rungs = None


# -- decode attention op ------------------------------------------------------


def test_attention_all_masked_keys_returns_zeros_not_nan():
    """A query row whose keys are ALL invalid (the empty-cache decode
    edge) must return zeros, not NaN — ``exp(-inf - -inf)`` would
    poison the softmax without the finite fill + explicit zero +
    denominator clamp."""
    from znicz_tpu.ops.attention import attention

    rng = np.random.default_rng(7)
    q = rng.normal(size=(2, 1, 2, 4)).astype(np.float32)
    k = rng.normal(size=(2, 6, 2, 4)).astype(np.float32)
    v = rng.normal(size=(2, 6, 2, 4)).astype(np.float32)
    out = np.asarray(attention(q, k, v,
                               k_valid=np.zeros((2, 6), bool)))
    assert np.all(np.isfinite(out))
    np.testing.assert_array_equal(out, np.zeros_like(out))


def test_attention_guard_bit_identical_with_valid_keys():
    """Rows with >= 1 valid key are BIT-identical to the unguarded
    softmax over just the valid prefix: masked probabilities are exact
    zeros, and adding exact zeros never perturbs a float sum."""
    from znicz_tpu.ops.attention import attention

    rng = np.random.default_rng(11)
    q = rng.normal(size=(1, 1, 2, 4)).astype(np.float32)
    k = rng.normal(size=(1, 6, 2, 4)).astype(np.float32)
    v = rng.normal(size=(1, 6, 2, 4)).astype(np.float32)
    for n_valid in (1, 3, 6):
        k_valid = np.zeros((1, 6), bool)
        k_valid[:, :n_valid] = True
        guarded = np.asarray(attention(q, k, v, k_valid=k_valid))
        plain = np.asarray(attention(q, k[:, :n_valid], v[:, :n_valid]))
        np.testing.assert_array_equal(guarded, plain)


def test_decode_attention_matches_causal_row():
    """``cache_append`` + ``decode_attention`` at fill ``t`` equals row
    ``t`` of the full causal forward: the unwritten cache tail carries
    exactly zero probability mass.  Different executables (q len 1 vs
    S, cache len C vs S) reduce in different orders, so the repo's
    per-executable 0-ULP rule makes this a ~1-ULP band, not bytes."""
    from znicz_tpu.ops.attention import (attention, cache_append,
                                         decode_attention)

    rng = np.random.default_rng(13)
    S, C = 5, 8
    q = rng.normal(size=(1, S, 2, 4)).astype(np.float32)
    k = rng.normal(size=(1, S, 2, 4)).astype(np.float32)
    v = rng.normal(size=(1, S, 2, 4)).astype(np.float32)
    import jax.numpy as jnp

    full = np.asarray(attention(q, k, v, causal=True))
    kc = jnp.zeros((1, C, 2, 4), jnp.float32)
    vc = jnp.zeros((1, C, 2, 4), jnp.float32)
    for t in range(S):
        tt = np.asarray([t], np.int32)
        kc = cache_append(kc, k[:, t], tt)
        vc = cache_append(vc, v[:, t], tt)
        step = np.asarray(decode_attention(q[:, t:t + 1], kc, vc, tt))
        np.testing.assert_allclose(step[:, 0], full[:, t],
                                   rtol=1e-6, atol=1e-6)


def test_paged_decode_attention_bit_exact_vs_contiguous():
    """The paged path is the contiguous path plus a pure gather:
    ``paged_gather`` over a row's page table reproduces its contiguous
    cache EXACTLY, so ``paged_decode_attention`` is bit-identical to
    ``decode_attention`` over the same values — per fixed executable,
    the ISSUE 19 correctness contract.  Scratch table slots past the
    fill sit behind ``k_valid`` like the contiguous unwritten tail."""
    from znicz_tpu.ops.attention import (decode_attention, paged_append,
                                         paged_decode_attention,
                                         paged_gather)

    rng = np.random.default_rng(17)
    ps, n_pages, h, d = 4, 6, 2, 4
    pool_k = rng.normal(size=(n_pages + 1, ps, h, d)).astype(np.float32)
    pool_v = rng.normal(size=(n_pages + 1, ps, h, d)).astype(np.float32)
    # two rows: row 0 owns pages [3, 1], row 1 pages [4, *scratch pad*]
    table = np.asarray([[3, 1], [4, n_pages]], np.int32)
    t = np.asarray([6, 2], np.int32)          # fills (page 1 mid, page 0)
    gk = np.asarray(paged_gather(pool_k, table))
    np.testing.assert_array_equal(gk[0, :ps], pool_k[3])
    np.testing.assert_array_equal(gk[0, ps:], pool_k[1])
    q = rng.normal(size=(2, 1, h, d)).astype(np.float32)
    paged = np.asarray(paged_decode_attention(q, pool_k, pool_v,
                                              table, t))
    contig = np.asarray(decode_attention(
        q, paged_gather(pool_k, table), paged_gather(pool_v, table), t))
    np.testing.assert_array_equal(paged, contig)
    # append lands at (table[i, t//ps], t%ps) — and the pad row's
    # scratch page never aliases a real one
    import jax.numpy as jnp

    row = rng.normal(size=(2, h, d)).astype(np.float32)
    out = np.asarray(paged_append(jnp.asarray(pool_k), table, row, t))
    np.testing.assert_array_equal(out[1, 6 % ps], row[0])  # page 1
    np.testing.assert_array_equal(out[4, 2], row[1])
    np.testing.assert_array_equal(out[3], pool_k[3])       # untouched


# -- page pool bookkeeping (leak audit satellite) ------------------------------


def test_page_pool_refcount_accounting():
    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, page_size=8, num_pages=4, slots=2,
                    prefix_cache=False)
    assert g.page_rungs == (1, 2, 4)
    assert g.max_ctx == 32
    assert g.executables() == ((len(g.prefill_rungs)
                                + len(g.decode_rungs)) * 3 + 1)
    # alloc to exhaustion; scratch is never handed out
    pages = [g.alloc_page() for _ in range(4)]
    assert sorted(pages) == [0, 1, 2, 3] and g.scratch not in pages
    assert g.alloc_page() is None
    assert g.pages_active() == 4 and g.occupancy() == 1.0
    # refcounted sharing: a second holder keeps the page alive
    g.addref(pages[0])
    g.decref(pages[0])
    assert g.pages_active() == 4
    g.release_pages(pages)
    assert g.pages_active() == 0 and g.pages_leaked() == 0
    st = g.stats()
    assert st["pages_free"] == 4 and st["pages_leaked"] == 0
    # over-release is a caught invariant violation, not silent rot
    p = g.alloc_page()
    g.decref(p)
    with pytest.raises(AssertionError):
        g.decref(p)


def test_prefix_index_eviction_under_pressure():
    """Idle index-held pages are reclaimed LRU-first when the pool
    runs dry — a cached prefix costs nothing until allocation wants
    the page back; pages shared with a LIVE request are never torn
    away."""
    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, page_size=8, num_pages=4, slots=2)
    rng = np.random.default_rng(19)
    p1 = rng.integers(1, VOCAB, size=8)
    _, pages1 = _greedy(g, p1, 1)
    g.prefix.register(p1, pages1)
    g.release_pages(pages1)
    assert g.stats()["prefix_pages"] == 1
    assert g.pages_active() == 1              # the index residue
    # a live hit pins the page: exhaust the pool, eviction must refuse
    held, covered = g.prefix.lookup(p1)
    assert covered == 8
    others = [g.alloc_page() for _ in range(3)]
    assert all(p is not None for p in others)
    assert g.alloc_page() is None             # indexed page is SHARED
    assert g.stats()["prefix_pages"] == 1
    # release the request: now pressure evicts the idle entry
    g.release_pages(held)
    got = g.alloc_page()
    assert got is not None
    assert g.stats()["prefix_pages"] == 0
    assert int(g._pm["evictions"].value) >= 1
    g.release_pages(others + [got])
    assert g.pages_active() == 0 and g.pages_leaked() == 0


# -- paged decode + chunked prefill vs the classic plane -----------------------


def test_paged_decode_parity_with_full_forward():
    """Greedy decode through the paged pool — chunked prefill, then
    per-token decode across page boundaries (1 -> 4 pages) — matches
    the classic full-forward teacher-forced on the same growing prefix
    at every step.  Different executables, so a numerical band, not
    bytes."""
    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, page_size=8, num_pages=16, slots=2)
    runner = g.runner
    rng = np.random.default_rng(17)
    prompt = rng.integers(1, VOCAB, size=5).astype(np.uint8)
    pages = [g.alloc_page()]
    x = np.zeros((1, 8), runner.dtype)
    x[0, :5] = prompt
    tok, _, logits, _ = g.prefill(x, [0], [5], [pages], [0.0], [0], [0])
    toks = [int(tok[0])]
    steps = [logits[0]]
    t = 5
    for _ in range(20):
        if t % g.page_size == 0:
            pages.append(g.alloc_page())
        tok, _, logits, _ = g.decode([pages], [toks[-1]], [t],
                                     [0.0], [0], [0])
        toks.append(int(tok[0]))
        steps.append(logits[0])
        t += 1
    assert len(pages) == 4                    # crossed three boundaries
    prefix = list(prompt) + toks[:-1]
    xb = np.zeros((1, 32), runner.dtype)
    xb[0, :len(prefix)] = prefix
    full = runner.infer(xb)[0]
    for i, row in enumerate(steps):
        np.testing.assert_allclose(row, full[len(prompt) - 1 + i],
                                   rtol=1e-5, atol=1e-6)
    g.release_pages(pages)
    assert g.pages_active() == 0 and g.pages_leaked() == 0


def test_chunked_prefill_matches_monolithic():
    """A 24-token prompt prefilled in three 8-token chunks produces
    the same next-token logits as ONE monolithic full forward over the
    prompt — within the established cross-executable band (the chunks
    run a different executable than the full forward)."""
    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, page_size=8, num_pages=16, slots=2)
    rng = np.random.default_rng(23)
    prompt = rng.integers(1, VOCAB, size=24).astype(np.uint8)
    pages = []
    for i in range(3):
        pages.append(g.alloc_page())
        x = np.zeros((1, 8), g.runner.dtype)
        x[0] = prompt[i * 8:(i + 1) * 8]
        tok, _, logits, _ = g.prefill(x, [i * 8], [8], [pages],
                                      [0.0], [0], [0])
    xb = np.zeros((1, 32), g.runner.dtype)
    xb[0, :24] = prompt
    full = g.runner.infer(xb)[0]
    np.testing.assert_allclose(logits[0], full[23], rtol=1e-5,
                               atol=1e-6)
    assert int(tok[0]) == int(np.argmax(full[23]))
    g.release_pages(pages)


def test_prefix_hit_bit_exact_vs_cold_prefill():
    """A prompt whose full pages hit the prefix index decodes
    BIT-identically to its cold prefill: with ``prefill_chunk ==
    page_size`` the hit's tail chunk replays the exact executable grid
    the cold run used, and decode gathers the very same page values.
    Logits equal to the byte, not a band."""
    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, page_size=8, num_pages=16, slots=2,
                    prefill_chunk=8)
    rng = np.random.default_rng(29)
    prompt = rng.integers(1, VOCAB, size=20).astype(np.uint8)  # 2 full+4

    def run(expect_hit):
        hits0 = int(g._pm["hits"].value)
        pages, covered = g.prefix.lookup(prompt)
        assert (covered == 16) == expect_hit
        assert (int(g._pm["hits"].value) == hits0 + 1) == expect_hit
        toks = []
        rows = []
        t0 = covered
        while t0 < 20:
            n = min(8, 20 - t0)
            while len(pages) < -(-(t0 + n) // 8):
                pages.append(g.alloc_page())
            x = np.zeros((1, 8), g.runner.dtype)
            x[0, :n] = prompt[t0:t0 + n]
            tok, _, logits, _ = g.prefill(x, [t0], [n], [pages],
                                          [0.0], [0], [0])
            t0 += n
        toks.append(int(tok[0]))
        rows.append(np.asarray(logits[0]))
        t = 20
        for _ in range(6):
            if t % 8 == 0:
                pages.append(g.alloc_page())
            tok, _, logits, _ = g.decode([pages], [toks[-1]], [t],
                                         [0.0], [0], [0])
            toks.append(int(tok[0]))
            rows.append(np.asarray(logits[0]))
            t += 1
        g.prefix.register(prompt, pages)
        return toks, rows, pages

    cold_t, cold_r, cold_p = run(expect_hit=False)
    g.release_pages(cold_p)
    hit_t, hit_r, hit_p = run(expect_hit=True)
    g.release_pages(hit_p)
    assert cold_t == hit_t
    for a, b in zip(cold_r, hit_r):
        np.testing.assert_array_equal(a, b)
    assert int(g._pm["tokens_avoided"].value) == 16
    assert g.pages_leaked() == 0


def test_cow_divergence_keeps_shared_pages_immutable():
    """Copy-on-write: a second request claiming a full shared page and
    then writing into it (the full-coverage recompute) writes into a
    COPY — the donor's page bytes never change, so requests sharing a
    prefix can never see each other's keys."""
    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, page_size=8, num_pages=16, slots=2)
    rng = np.random.default_rng(31)
    prompt = rng.integers(1, VOCAB, size=8).astype(np.uint8)  # 1 full page
    toksA, pagesA = _greedy(g, prompt, 4)
    g.prefix.register(prompt, pagesA)
    shared = pagesA[0]
    layer = next(iter(g.pk))
    before_k = np.asarray(g.pk[layer][shared]).copy()
    # request B: full coverage -> recompute the last prompt token into
    # the shared page, which must COW first
    pagesB, covered = g.prefix.lookup(prompt)
    assert covered == 8 and pagesB == [shared]
    fresh = g.alloc_page()
    g.copy_page(shared, fresh)
    g.decref(shared)
    pagesB[0] = fresh
    x = np.zeros((1, 8), g.runner.dtype)
    x[0, 0] = prompt[7]
    tokB, _, _, _ = g.prefill(x, [7], [1], [pagesB], [0.0], [0], [0])
    toksB = [int(tokB[0])]
    t = 8
    for _ in range(3):
        if t % 8 == 0:
            pagesB.append(g.alloc_page())
        tokB, _, _, _ = g.decode([pagesB], [toksB[-1]], [t],
                                 [0.0], [0], [0])
        toksB.append(int(tokB[0]))
        t += 1
    # B's divergent writes landed in the COPY: the donor's shared page
    # is bit-untouched, and B's greedy continuation agrees with A's
    # (the page values B read are identical to what A wrote)
    np.testing.assert_array_equal(np.asarray(g.pk[layer][shared]),
                                  before_k)
    assert toksB == toksA
    assert g.page_ref[fresh] == 1
    g.release_pages(pagesA)
    g.release_pages(pagesB)
    # residue: exactly the index-held page remains
    assert g.pages_active() == 1 and g.pages_leaked() == 0
    assert g.stats()["prefix_pages"] == 1


# -- continuous batching scheduler --------------------------------------------


def _run_to_completion(sched, max_rounds=400):
    replies = []
    for _ in range(max_rounds):
        if not sched.work_available():
            break
        _, reps = sched.step()
        replies.extend(reps)
    return replies


def test_scheduler_continuous_batching():
    """Mixed generations through the scheduler alone: co-batched decode
    ticks, chunked prefill of a long prompt, mid-batch page release,
    context-window truncation, resend dedup, and determinism on a
    re-run (which rides the prefix cache the second time)."""
    from znicz_tpu.serving.batcher import GenSeq, GenerationScheduler

    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, page_size=8, num_pages=24, slots=4)
    sched = GenerationScheduler(g, max_new_cap=64)
    m = {k: c.value for k, c in sched._m.items()}
    rng = np.random.default_rng(19)

    def seqs():
        return [GenSeq(rng.integers(1, VOCAB, size=3), 4, req_id=1),
                GenSeq(rng.integers(1, VOCAB, size=5), 12, req_id=2),
                GenSeq(rng.integers(1, VOCAB, size=7), 6, temperature=0.8,
                       seed=41, req_id=3),
                # 6 + 30 outgrows the 32-token context -> truncated
                GenSeq(rng.integers(1, VOCAB, size=6), 30, req_id=4),
                # 20 tokens = three prefill chunks before decoding
                GenSeq(rng.integers(1, VOCAB, size=20), 4, req_id=5)]

    first = seqs()
    for s in first:
        assert sched.submit(s) is None
    # a resend of an in-flight (client, req_id) is absorbed silently
    assert sched.submit(GenSeq(first[0].prompt, 4, req_id=1)) is None
    assert sched._m["gen_dedup"].value == m["gen_dedup"] + 1
    replies = _run_to_completion(sched)
    finals = {r["req_id"]: r for _, r in replies if not r.get("partial")}
    assert set(finals) == {1, 2, 3, 4, 5}
    assert all(r["ok"] for r in finals.values())
    assert len(finals[1]["tokens"]) == 4
    assert len(finals[2]["tokens"]) == 12
    assert "truncated" in finals[4] and len(finals[4]["tokens"]) < 30
    assert sched._m["gen_truncated"].value == m["gen_truncated"] + 1
    # the 20-token prompt took >= 3 chunk dispatches
    assert sched._m["prefill_batches"].value >= m["prefill_batches"] + 3
    # mid-batch release: pages return as sequences finish on their own
    # schedule; only the prefix-index residue stays allocated
    assert g.pages_leaked() == 0
    assert g.pages_active() == g.stats()["prefix_pages"]
    assert sched._m["decode_batches"].value > m["decode_batches"]
    # determinism: the same stream again emits the same tokens — the
    # second pass HITS the prefix cache and must not diverge
    hits0 = g.stats()["prefix_hits"]
    rng = np.random.default_rng(19)
    again = seqs()
    for s in again:
        assert sched.submit(s) is None
    replies2 = _run_to_completion(sched)
    finals2 = {r["req_id"]: r for _, r in replies2
               if not r.get("partial")}
    for rid in (1, 2, 3, 4, 5):
        np.testing.assert_array_equal(finals[rid]["tokens"],
                                      finals2[rid]["tokens"])
    assert g.stats()["prefix_hits"] > hits0


def test_scheduler_refusals_and_deadline():
    from znicz_tpu.serving.batcher import GenSeq, GenerationScheduler

    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, page_size=8, num_pages=16, slots=2)
    sched = GenerationScheduler(g, max_new_cap=16)
    ref = sched.submit(GenSeq(np.ones(33, np.uint8), 4))
    assert ref is not None and "context window" in ref \
        and ref.policy == "oversized"
    ref = sched.submit(GenSeq(np.ones(3, np.uint8), 17))
    assert ref is not None \
        and "root.common.serving.generate.max_new_tokens" in ref
    # a pending deadline expiry ships a readable partial
    s = GenSeq(np.ones(3, np.uint8), 4, deadline_s=-0.01)
    assert sched.submit(s) is None
    _, reps = sched.step()
    timed = [r for _, r in reps if r.get("timed_out")]
    assert len(timed) == 1 and timed[0]["policy"] == "deadline"
    assert g.pages_active() == 0


def test_scheduler_page_pressure_flood_no_leaks():
    """A flood against a page pool sized for ONE request plus a tight
    pending bound: overflow submits are refused with the ``shed``
    policy, everything admitted finishes (page pressure stalls rows,
    never deadlocks them), a deadline expiry mid-generation ships its
    ``deadline`` partial AND releases its pages, and the pool comes
    back whole — the leak-audit satellite's terminal invariant."""
    from znicz_tpu.serving.batcher import GenSeq, GenerationScheduler

    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, page_size=8, num_pages=4, slots=2,
                    prefix_cache=False)
    sched = GenerationScheduler(g, max_new_cap=8, pending_bound=3)
    refused0 = sched._m["gen_refused"].value
    rng = np.random.default_rng(23)

    def seq(rid, max_new=2, size=3):
        return GenSeq(rng.integers(1, VOCAB, size=size).astype(np.uint8),
                      max_new, req_id=rid)

    for rid in (1, 2, 3):
        assert sched.submit(seq(rid)) is None
    ref = sched.submit(seq(4))               # 4th: queue at bound
    assert ref is not None and ref.policy == "shed"
    assert "generation queue at bound" in ref
    assert sched._m["gen_refused"].value == refused0 + 1
    finals = {r["req_id"]: r for _, r in _run_to_completion(sched)
              if not r.get("partial")}
    assert set(finals) == {1, 2, 3}
    assert all(r["ok"] and len(r["tokens"]) == 2
               for r in finals.values())
    assert g.pages_active() == 0

    # deadline expiry WHILE holding pages: the partial ships with the
    # 'deadline' policy and every page returns to the pool
    a, b = seq(10, max_new=6, size=9), seq(11, max_new=6, size=9)
    assert sched.submit(a) is None and sched.submit(b) is None
    for _ in range(200):                     # drive until b holds pages
        sched.step()
        if b.pages:
            break
    assert b.pages
    b.t_deadline = 1e-9                      # absolute clock: expired
    _, reps = sched.step()
    timed = [r for _, r in reps if r.get("timed_out")]
    assert len(timed) == 1 and timed[0]["req_id"] == 11
    assert timed[0]["policy"] == "deadline"
    _run_to_completion(sched)
    # the pool invariant the whole satellite rides: every page is back
    # exactly once (free list duplicate-free), refcounts all zero
    assert g.pages_active() == 0 and g.pages_leaked() == 0
    assert sorted(g._free_pages) == list(range(g.num_pages))
    assert not g.page_ref.any()
    # the queue is open again after the drain
    assert sched.submit(seq(20)) is None
    finals = {r["req_id"]: r for _, r in _run_to_completion(sched)
              if not r.get("partial")}
    assert finals[20]["ok"]
    assert g.pages_active() == 0


@pytest.mark.slow
def test_page_refcounts_return_to_prefix_residue():
    """Leak audit with sharing ON: after every termination flavor (ok,
    deadline partial, drain) the pool holds EXACTLY the shared-prefix
    residue — every allocated page is refcount-1 and index-held, and
    ``pages_leaked`` stays 0 throughout."""
    from znicz_tpu.serving.batcher import GenSeq, GenerationScheduler

    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, page_size=8, num_pages=24, slots=4)
    sched = GenerationScheduler(g, max_new_cap=16)
    rng = np.random.default_rng(37)
    shared = rng.integers(1, VOCAB, size=16).astype(np.uint8)

    def residue_ok():
        st = g.stats()
        assert st["pages_leaked"] == 0
        assert st["pages_active"] == st["prefix_pages"]
        held = [p for p in range(g.num_pages) if g.page_ref[p] > 0]
        assert all(g.page_ref[p] == 1 for p in held)
        assert len(held) == st["prefix_pages"]

    # ok finishes (two share the 16-token prefix)
    for rid in (1, 2):
        tail = rng.integers(1, VOCAB, size=3).astype(np.uint8)
        assert sched.submit(GenSeq(np.concatenate([shared, tail]), 3,
                                   req_id=rid)) is None
    finals = {r["req_id"]: r for _, r in _run_to_completion(sched)
              if not r.get("partial")}
    assert finals[1]["ok"] and finals[2]["ok"]
    residue_ok()
    # deadline partial mid-generation
    s = GenSeq(np.concatenate(
        [shared, rng.integers(1, VOCAB, size=2).astype(np.uint8)]),
        12, req_id=3)
    assert sched.submit(s) is None
    for _ in range(50):
        sched.step()
        if s.tokens:
            break
    s.t_deadline = 1e-9
    _run_to_completion(sched)
    residue_ok()
    assert g.stats()["prefix_hits"] >= 1     # rid 3 claimed the prefix
    # drain (shutdown) with work in flight
    assert sched.submit(GenSeq(shared, 8, req_id=4)) is None
    sched.step()
    reps = sched.drain()
    assert any(r.get("policy") == "draining" for _, r in reps)
    residue_ok()


def test_on_device_vs_host_sampling_greedy_bit_identical():
    """The ``on_device_sampling`` knob only changes WHAT ships over
    D2H — (b,) argmax tokens vs (b, vocab) logits argmax'd on the
    host — so greedy streams are bit-identical across it, and the
    device path moves a small fraction of the bytes."""
    from znicz_tpu.serving.batcher import GenSeq, GenerationScheduler

    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, page_size=8, num_pages=16, slots=2,
                    prefix_cache=False)
    rng = np.random.default_rng(41)
    prompt = rng.integers(1, VOCAB, size=5).astype(np.uint8)

    def run(on_device):
        sched = GenerationScheduler(g, max_new_cap=16,
                                    on_device_sampling=on_device)
        b0 = int(sched._m["fetch_bytes"].value)
        assert sched.submit(GenSeq(prompt, 8, req_id=1)) is None
        finals = {r["req_id"]: r
                  for _, r in _run_to_completion(sched)
                  if not r.get("partial")}
        return (finals[1]["tokens"],
                int(sched._m["fetch_bytes"].value) - b0)

    dev_toks, dev_bytes = run(on_device=True)
    host_toks, host_bytes = run(on_device=False)
    np.testing.assert_array_equal(dev_toks, host_toks)
    # tokens are 4 B/row vs vocab*4 B/row of logits
    assert dev_bytes * 4 <= host_bytes
    assert g.pages_active() == 0 and g.pages_leaked() == 0


def test_scheduler_logprobs_and_logits():
    """``return_logprobs`` ships one float per emitted token (both
    sampling paths agree within float32 vs float64 noise) and
    ``return_logits`` still works with fused sampling on."""
    from znicz_tpu.serving.batcher import GenSeq, GenerationScheduler

    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, page_size=8, num_pages=16, slots=2,
                    prefix_cache=False)
    rng = np.random.default_rng(43)
    prompt = rng.integers(1, VOCAB, size=5).astype(np.uint8)

    def run(on_device):
        sched = GenerationScheduler(g, max_new_cap=16,
                                    on_device_sampling=on_device)
        assert sched.submit(GenSeq(prompt, 5, req_id=1,
                                   return_logprobs=True,
                                   return_logits=True)) is None
        finals = {r["req_id"]: r
                  for _, r in _run_to_completion(sched)
                  if not r.get("partial")}
        return finals[1]

    a = run(on_device=True)
    b = run(on_device=False)
    assert a["logprobs"].shape == (5,) and a["logits"].shape == (5, VOCAB)
    np.testing.assert_array_equal(a["tokens"], b["tokens"])
    np.testing.assert_allclose(a["logprobs"], b["logprobs"],
                               rtol=1e-5, atol=1e-6)
    # the logprob IS the log-softmax of the shipped logits row
    z = a["logits"][0].astype(np.float64)
    z -= z.max()
    want = z[a["tokens"][0]] - np.log(np.exp(z).sum())
    np.testing.assert_allclose(a["logprobs"][0], want, rtol=1e-5,
                               atol=1e-6)
    assert g.pages_active() == 0


# -- e2e service --------------------------------------------------------------


def test_e2e_generate_service(_generate_config):
    """The ``generate`` request kind end-to-end: greedy + seeded
    determinism over the wire, streamed partials, logprobs, refusals
    naming the config knob, neighbor invisibility, truncation, stats
    export, and jit-cache hygiene over a repeated mixed stream."""
    from znicz_tpu.serving import InferenceClient, InferenceServer
    from znicz_tpu.serving.client import InferenceError

    wf = _charlm_wf(seq_len=32)
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=1.0,
                          warmup=False).start()
    cli = InferenceClient(srv.endpoint, timeout=60)
    rng = np.random.default_rng(23)
    try:
        prompt = rng.integers(1, VOCAB, size=5).astype(np.uint8)
        # greedy determinism over the wire
        a = cli.generate(prompt, max_new_tokens=6)
        b = cli.generate(prompt, max_new_tokens=6)
        assert a["prompt_len"] == 5 and len(a["tokens"]) == 6
        np.testing.assert_array_equal(a["tokens"], b["tokens"])
        # seeded sampling determinism
        s1 = cli.generate(prompt, 6, temperature=0.9, top_k=8, seed=37)
        s2 = cli.generate(prompt, 6, temperature=0.9, top_k=8, seed=37)
        np.testing.assert_array_equal(s1["tokens"], s2["tokens"])
        # streamed partials arrive in order and match the final
        got = []
        rid = cli.submit_generate(prompt, 6, stream=True,
                                  on_token=lambda t, i: got.append((i, t)))
        fin = cli.result(rid)
        assert [i for i, _ in got] == list(range(6))
        np.testing.assert_array_equal([t for _, t in got], fin["tokens"])
        # token logprobs ride the token-sized reply
        lp = cli.generate(prompt, 4, return_logprobs=True)
        assert lp["logprobs"].shape == (4,)
        assert np.all(lp["logprobs"] <= 0)
        # neighbor invisibility: the greedy probe co-batched with
        # sampled neighbors answers exactly like it did solo
        rid_p = cli.submit_generate(prompt, 6)
        rids = [cli.submit_generate(
                    rng.integers(1, VOCAB, size=4).astype(np.uint8), 6,
                    temperature=1.1, seed=100 + k) for k in range(2)]
        reps = {r: cli.result(r) for r in [rid_p] + rids}
        np.testing.assert_array_equal(reps[rid_p]["tokens"], a["tokens"])
        # prefix reuse over the wire: a long prompt twice — the second
        # run computes only its unshared tail
        long_p = rng.integers(1, VOCAB, size=26).astype(np.uint8)
        st0 = srv.stats()["generate"]
        r1 = cli.generate(long_p, 4)
        st1 = srv.stats()["generate"]
        r2 = cli.generate(long_p, 4)
        st2 = srv.stats()["generate"]
        np.testing.assert_array_equal(r1["tokens"], r2["tokens"])
        cold = st1["prefill_tokens"] - st0["prefill_tokens"]
        warm = st2["prefill_tokens"] - st1["prefill_tokens"]
        assert cold == 26 and warm <= 2, (cold, warm)
        # refusals name the knob / window; service stays up
        with pytest.raises(InferenceError, match="context window"):
            cli.generate(np.ones(33, np.uint8), 4)
        with pytest.raises(InferenceError,
                           match="generate.max_new_tokens"):
            cli.generate(prompt, 10 ** 6)
        # context-window truncation is a readable finish, not an error
        t = cli.generate(prompt, 40)
        assert t.get("truncated") and len(t["tokens"]) < 40
        # stats + telemetry surface
        st = srv.stats()["generate"]
        assert st["gen_finished"] >= 8
        assert st["generated_tokens"] >= 8 * 6
        assert st["pages_leaked"] == 0
        assert st["pages_active"] == st["prefix_pages"]
        assert st["prefix_hits"] >= 1
        assert st["inter_token_p99_ms"] is not None
        # jit-cache hygiene: the same mixed stream again compiles NOTHING
        warm_c = srv.runner.compiles
        cache = srv.gen_sched.gen.jit_cache_size()
        cli.generate(prompt, 6)
        cli.generate(prompt, 6, temperature=0.9, top_k=8, seed=37)
        cli.generate(prompt, 40)
        cli.generate(long_p, 4)
        assert srv.runner.compiles == warm_c
        assert srv.gen_sched.gen.jit_cache_size() in (None, cache)
    finally:
        cli.close()
        srv.stop()


def test_generate_disabled_is_refused_readably():
    from znicz_tpu.serving import InferenceClient, InferenceServer
    from znicz_tpu.serving.client import InferenceError

    root.common.serving.seq.rungs = [8, 32]
    try:
        wf = _charlm_wf(seq_len=32)
        srv = InferenceServer(wf, max_batch=4, max_delay_ms=1.0,
                              warmup=False).start()
        cli = InferenceClient(srv.endpoint, timeout=30)
        try:
            with pytest.raises(InferenceError,
                               match="generate.*enabled|enabled.*generate"):
                cli.generate(np.ones(3, np.uint8), 4)
        finally:
            cli.close()
            srv.stop()
    finally:
        root.common.serving.seq.rungs = None


def test_web_status_generation_row(_generate_config):
    from znicz_tpu.serving import InferenceClient, InferenceServer
    from znicz_tpu.web_status import WebStatus

    wf = _charlm_wf(seq_len=32)
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=1.0,
                          warmup=False).start()
    status = WebStatus(port=0).start()
    cli = InferenceClient(srv.endpoint, timeout=30)
    try:
        status.register(wf)
        status.register_inference(srv)
        cli.generate(np.ones(5, np.uint8), 6)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/status.json") as r:
            snap = json.load(r)
        gen = snap["serving"]["generate"]
        assert gen["gen_finished"] >= 1
        assert gen["generated_tokens"] >= 6
        assert gen["page_size"] == 8
        assert gen["pages_leaked"] == 0
        assert gen["prefix_enabled"] is True
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/") as r:
            page = r.read().decode()
        assert "generation" in page and "KV pages" in page
        assert "prefix cache" in page and "COW copies" in page
    finally:
        cli.close()
        status.stop()
        srv.stop()


@pytest.mark.slow
def test_generate_chaos_soak(_generate_config):
    """Generations through a ChaosProxy (drop/corrupt/dup/delay both
    ways): every request eventually answers, resends of in-flight
    generations are deduplicated (never re-executed), greedy streams
    stay deterministic, nothing recompiles after the first pass, and
    the page pool ends at EXACTLY the shared-prefix residue — the
    leak-audit satellite under fault injection."""
    from znicz_tpu.parallel.chaos import ChaosProxy, FaultSchedule
    from znicz_tpu.serving import InferenceClient, InferenceServer

    wf = _charlm_wf(seq_len=32)
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=1.0,
                          warmup=False).start()
    schedule = FaultSchedule(seed=77, drop=0.08, corrupt=0.05,
                             duplicate=0.08, delay=0.05,
                             delay_s=(0.005, 0.03))
    front = "tcp://127.0.0.1:17699"
    proxy = ChaosProxy(front, srv.endpoint, schedule)
    proxy.start()
    cli = InferenceClient(front, timeout=120,
                          resend_after_s=0.3, breaker_failures=0)
    rng = np.random.default_rng(29)
    try:
        # clean-path references (direct, pre-chaos traffic shapes);
        # half the prompts share an 8-token prefix page to keep the
        # prefix cache and COW machinery in the blast radius
        ref_cli = InferenceClient(srv.endpoint, timeout=60)
        shared = rng.integers(1, VOCAB, size=8).astype(np.uint8)
        prompts = []
        for i in range(12):
            tail = rng.integers(1, VOCAB,
                                size=int(rng.integers(2, 8))
                                ).astype(np.uint8)
            prompts.append(np.concatenate([shared, tail])
                           if i % 2 else tail)
        want = [ref_cli.generate(p, 8)["tokens"] for p in prompts]
        ref_cli.close()
        # concurrent chaos traffic co-batches deeper than the serial
        # reference pass — warm the full executable family so the
        # zero-recompile assert sees a complete baseline
        srv.gen_sched.gen.warmup()
        warm = srv.runner.compiles
        rids = [cli.submit_generate(p, 8) for p in prompts]
        got = {}
        deadline = time.time() + 90
        while len(got) < len(rids) and time.time() < deadline:
            for rep in cli.collect(0.05):
                if rep.get("ok") and not rep.get("partial"):
                    got[rep["req_id"]] = rep["tokens"]
        assert len(got) == len(rids), (len(got), len(rids))
        for rid, w in zip(rids, want):
            np.testing.assert_array_equal(got[rid], w)
        assert srv.runner.compiles == warm
        # terminal page invariant under chaos: every non-free page is
        # exactly the refcount-1 prefix-index residue, none leaked
        g = srv.gen_sched.gen
        st = g.stats()
        assert st["pages_leaked"] == 0
        assert st["pages_active"] == st["prefix_pages"]
        held = [p for p in range(g.num_pages) if g.page_ref[p] > 0]
        assert all(g.page_ref[p] == 1 for p in held)
    finally:
        cli.close()
        proxy.stop()
        srv.stop()
