"""Autoregressive generation serving (ISSUE 16): the decode-attention
NaN guard, KV-pool slot/migration accounting, decode-vs-full-forward
parity ACROSS a cache-rung migration, the continuous-batching
scheduler (mid-batch release, determinism, resend dedup), the e2e
``generate`` service (streaming, refusals, neighbor invisibility,
repeat-stream jit-cache hygiene), the web panel generation row, and a
chaos soak (slow)."""

import json
import time
import urllib.request

import numpy as np
import pytest

from znicz_tpu.core.config import root

VOCAB = 32


def _charlm_wf(seq_len=32):
    from znicz_tpu.core import prng
    from znicz_tpu.samples.charlm import CharLMWorkflow

    prng.reset(1013)
    root.charlm.loader.update({"n_train": 64, "n_valid": 16, "n_test": 0,
                               "seq_len": seq_len, "minibatch_size": 16})
    root.charlm.model.update({"vocab": VOCAB, "embed": 32, "heads": 2,
                              "ffn": 64})
    wf = CharLMWorkflow()
    wf.initialize(device=None)
    return wf


def _gen_runner(wf, cache_rungs=(8, 16, 32), slots=2,
                prompt_rungs=(8,)):
    from znicz_tpu.serving.model import ModelRunner

    runner = ModelRunner(wf)
    return runner.enable_generation(cache_rungs=list(cache_rungs),
                                    slots=slots,
                                    prompt_rungs=list(prompt_rungs))


@pytest.fixture()
def _generate_config():
    """Enable the generation plane for a server test, restore after."""
    root.common.serving.seq.rungs = [8, 32]
    root.common.serving.generate.update({
        "enabled": True, "cache_rungs": [8, 16, 32], "slots": 4})
    yield
    root.common.serving.generate.enabled = False
    root.common.serving.seq.rungs = None


# -- decode attention op ------------------------------------------------------


def test_attention_all_masked_keys_returns_zeros_not_nan():
    """A query row whose keys are ALL invalid (the empty-cache decode
    edge) must return zeros, not NaN — ``exp(-inf - -inf)`` would
    poison the softmax without the finite fill + explicit zero +
    denominator clamp."""
    from znicz_tpu.ops.attention import attention

    rng = np.random.default_rng(7)
    q = rng.normal(size=(2, 1, 2, 4)).astype(np.float32)
    k = rng.normal(size=(2, 6, 2, 4)).astype(np.float32)
    v = rng.normal(size=(2, 6, 2, 4)).astype(np.float32)
    out = np.asarray(attention(q, k, v,
                               k_valid=np.zeros((2, 6), bool)))
    assert np.all(np.isfinite(out))
    np.testing.assert_array_equal(out, np.zeros_like(out))


def test_attention_guard_bit_identical_with_valid_keys():
    """Rows with >= 1 valid key are BIT-identical to the unguarded
    softmax over just the valid prefix: masked probabilities are exact
    zeros, and adding exact zeros never perturbs a float sum."""
    from znicz_tpu.ops.attention import attention

    rng = np.random.default_rng(11)
    q = rng.normal(size=(1, 1, 2, 4)).astype(np.float32)
    k = rng.normal(size=(1, 6, 2, 4)).astype(np.float32)
    v = rng.normal(size=(1, 6, 2, 4)).astype(np.float32)
    for n_valid in (1, 3, 6):
        k_valid = np.zeros((1, 6), bool)
        k_valid[:, :n_valid] = True
        guarded = np.asarray(attention(q, k, v, k_valid=k_valid))
        plain = np.asarray(attention(q, k[:, :n_valid], v[:, :n_valid]))
        np.testing.assert_array_equal(guarded, plain)


def test_decode_attention_matches_causal_row():
    """``cache_append`` + ``decode_attention`` at fill ``t`` equals row
    ``t`` of the full causal forward: the unwritten cache tail carries
    exactly zero probability mass.  Different executables (q len 1 vs
    S, cache len C vs S) reduce in different orders, so the repo's
    per-executable 0-ULP rule makes this a ~1-ULP band, not bytes."""
    from znicz_tpu.ops.attention import (attention, cache_append,
                                         decode_attention)

    rng = np.random.default_rng(13)
    S, C = 5, 8
    q = rng.normal(size=(1, S, 2, 4)).astype(np.float32)
    k = rng.normal(size=(1, S, 2, 4)).astype(np.float32)
    v = rng.normal(size=(1, S, 2, 4)).astype(np.float32)
    import jax.numpy as jnp

    full = np.asarray(attention(q, k, v, causal=True))
    kc = jnp.zeros((1, C, 2, 4), jnp.float32)
    vc = jnp.zeros((1, C, 2, 4), jnp.float32)
    for t in range(S):
        tt = np.asarray([t], np.int32)
        kc = cache_append(kc, k[:, t], tt)
        vc = cache_append(vc, v[:, t], tt)
        step = np.asarray(decode_attention(q[:, t:t + 1], kc, vc, tt))
        np.testing.assert_allclose(step[:, 0], full[:, t],
                                   rtol=1e-6, atol=1e-6)


# -- KV pool bookkeeping ------------------------------------------------------


def test_kv_pool_slot_accounting():
    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, cache_rungs=(8, 16), slots=2)
    # rung resolution
    assert g._rung_for(5) == 8
    assert g._rung_for(9) == 16
    assert g._rung_for(17) is None
    # alloc to exhaustion, release recycles; scratch is never handed out
    a, b = g.alloc(8), g.alloc(8)
    assert {a, b} == {0, 1} and g.scratch not in (a, b)
    assert g.alloc(8) is None                 # rung exhausted, not scratch
    assert g.slots_active() == 2
    assert g.occupancy() == pytest.approx(0.5)
    g.release(8, a)
    assert g.alloc(8) == a
    for s in (a, b):
        g.release(8, s)
    assert g.slots_active() == 0
    st = g.stats()
    assert st["slots_total"] == 4
    assert st["executables"] == (len(g.prefill_rungs) * 1
                                 + len(g.decode_rungs) * 2 + 1)


def test_decode_parity_across_cache_rung_migration():
    """Greedy decode through the KV pool — prefill, per-token decode,
    and TWO rung migrations (8 -> 16 -> 32) — matches the classic
    full-forward teacher-forced on the same growing prefix at every
    step.  Different executables, so a numerical band, not bytes."""
    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, cache_rungs=(8, 16, 32), slots=2)
    runner = g.runner
    rng = np.random.default_rng(17)
    prompt = rng.integers(1, VOCAB, size=5).astype(np.uint8)
    rung = g._rung_for(len(prompt))
    slot = g.alloc(rung)
    x = np.zeros((1, 8), runner.dtype)
    x[0, :5] = prompt
    logits, _ = g.prefill(x, [5], rung, [slot])
    toks = [int(np.argmax(logits[0]))]
    steps = [logits[0]]
    t = len(prompt)
    migrations = 0
    for _ in range(20):
        if t >= rung:                         # fill outgrew the rung
            dst = g._rung_for(t + 1)
            ds = g.alloc(dst)
            g.migrate(rung, slot, dst, ds)
            g.release(rung, slot)
            rung, slot = dst, ds
            migrations += 1
        logits, _ = g.decode(rung, [slot], [toks[-1]], [t])
        toks.append(int(np.argmax(logits[0])))
        steps.append(logits[0])
        t += 1
    assert migrations == 2                    # crossed 8->16 and 16->32
    # classic plane: teacher-force the same prefix, read each position
    prefix = list(prompt) + toks[:-1]
    xb = np.zeros((1, 32), runner.dtype)
    xb[0, :len(prefix)] = prefix
    full = runner.infer(xb)[0]
    for i, row in enumerate(steps):
        np.testing.assert_allclose(row, full[len(prompt) - 1 + i],
                                   rtol=1e-5, atol=1e-6)
    g.release(rung, slot)
    assert g.slots_active() == 0


# -- continuous batching scheduler --------------------------------------------


def _run_to_completion(sched, max_rounds=400):
    replies = []
    for _ in range(max_rounds):
        if not sched.work_available():
            break
        _, reps = sched.step()
        replies.extend(reps)
    return replies


def test_scheduler_continuous_batching():
    """Mixed generations through the scheduler alone: co-batched decode
    ticks, mid-batch slot release, rung migration, ladder-top
    truncation, resend dedup, and seeded determinism on a re-run."""
    from znicz_tpu.serving.batcher import GenSeq, GenerationScheduler

    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, cache_rungs=(8, 16, 32), slots=4)
    sched = GenerationScheduler(g, max_new_cap=64)
    m = {k: c.value for k, c in sched._m.items()}
    rng = np.random.default_rng(19)

    def seqs():
        return [GenSeq(rng.integers(1, VOCAB, size=3), 4, req_id=1),
                GenSeq(rng.integers(1, VOCAB, size=5), 12, req_id=2),
                GenSeq(rng.integers(1, VOCAB, size=7), 6, temperature=0.8,
                       seed=41, req_id=3),
                # 6 + 30 outgrows the 32-rung ladder top -> truncated
                GenSeq(rng.integers(1, VOCAB, size=6), 30, req_id=4)]

    first = seqs()
    for s in first:
        assert sched.submit(s) is None
    # a resend of an in-flight (client, req_id) is absorbed silently
    assert sched.submit(GenSeq(first[0].prompt, 4, req_id=1)) is None
    assert sched._m["gen_dedup"].value == m["gen_dedup"] + 1
    replies = _run_to_completion(sched)
    finals = {r["req_id"]: r for _, r in replies if not r.get("partial")}
    assert set(finals) == {1, 2, 3, 4}
    assert all(r["ok"] for r in finals.values())
    assert len(finals[1]["tokens"]) == 4
    assert len(finals[2]["tokens"]) == 12
    assert "truncated" in finals[4] and len(finals[4]["tokens"]) < 30
    assert sched._m["migrations"].value > m["migrations"]
    assert sched._m["gen_truncated"].value == m["gen_truncated"] + 1
    # mid-batch release: short and long budgets finished on their own
    # schedule, and every slot is back in the pool
    assert g.slots_active() == 0
    assert sched._m["decode_batches"].value > m["decode_batches"]
    # determinism: the same stream (same seeds) emits the same tokens
    rng = np.random.default_rng(19)
    again = seqs()
    for s in again:
        assert sched.submit(s) is None
    replies2 = _run_to_completion(sched)
    finals2 = {r["req_id"]: r for _, r in replies2
               if not r.get("partial")}
    for rid in (1, 2, 3, 4):
        np.testing.assert_array_equal(finals[rid]["tokens"],
                                      finals2[rid]["tokens"])


def test_scheduler_refusals_and_deadline():
    from znicz_tpu.serving.batcher import GenSeq, GenerationScheduler

    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, cache_rungs=(8, 16, 32), slots=2,
                    prompt_rungs=(8, 16))
    sched = GenerationScheduler(g, max_new_cap=16)
    ref = sched.submit(GenSeq(np.ones(17, np.uint8), 4))
    assert ref is not None and "prompt" in ref and ref.policy == "oversized"
    ref = sched.submit(GenSeq(np.ones(3, np.uint8), 17))
    assert ref is not None \
        and "root.common.serving.generate.max_new_tokens" in ref
    # a pending deadline expiry ships a readable partial
    s = GenSeq(np.ones(3, np.uint8), 4, deadline_s=-0.01)
    assert sched.submit(s) is None
    _, reps = sched.step()
    timed = [r for _, r in reps if r.get("timed_out")]
    assert len(timed) == 1 and timed[0]["policy"] == "deadline"
    assert g.slots_active() == 0


# -- slot exhaustion + pending-bound flood (ISSUE 17 satellite) ----------------


def test_scheduler_slot_exhaustion_flood_no_leaks():
    """A flood against ONE KV slot per rung plus a tight pending
    bound: overflow submits are refused with the ``shed`` policy
    (never queued, never holding a slot), everything admitted
    finishes, a deadline expiry mid-generation ships its ``deadline``
    partial AND releases its slot, and the pool comes back whole —
    free lists full and duplicate-free."""
    from znicz_tpu.serving.batcher import GenSeq, GenerationScheduler

    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, cache_rungs=(8, 16), slots=1, prompt_rungs=(8,))
    sched = GenerationScheduler(g, max_new_cap=8, pending_bound=3)
    refused0 = sched._m["gen_refused"].value
    rng = np.random.default_rng(23)

    def seq(rid, max_new=2):
        return GenSeq(rng.integers(1, VOCAB, size=3).astype(np.uint8),
                      max_new, req_id=rid)

    for rid in (1, 2, 3):
        assert sched.submit(seq(rid)) is None
    ref = sched.submit(seq(4))               # 4th: queue at bound
    assert ref is not None and ref.policy == "shed"
    assert "generation queue at bound" in ref
    assert sched._m["gen_refused"].value == refused0 + 1
    # the flood drains: with one slot the three admitted generations
    # serialize through the pool, and all of them finish ok
    finals = {r["req_id"]: r for _, r in _run_to_completion(sched)
              if not r.get("partial")}
    assert set(finals) == {1, 2, 3}
    assert all(r["ok"] and len(r["tokens"]) == 2
               for r in finals.values())
    assert g.slots_active() == 0

    # deadline expiry WHILE holding a slot: the partial ships with the
    # 'deadline' policy and the slot returns to the pool
    a, b = seq(10, max_new=6), seq(11, max_new=6)
    assert sched.submit(a) is None and sched.submit(b) is None
    for _ in range(200):                     # drive until b owns a slot
        sched.step()
        if b.slot is not None:
            break
    assert b.slot is not None
    b.t_deadline = 1e-9                      # absolute clock: expired
    _, reps = sched.step()
    timed = [r for _, r in reps if r.get("timed_out")]
    assert len(timed) == 1 and timed[0]["req_id"] == 11
    assert timed[0]["policy"] == "deadline"
    _run_to_completion(sched)
    assert g.slots_active() == 0
    # the pool invariant the whole satellite rides: every slot is back
    # exactly once, and scratch was never handed out
    for rung, free in g._free.items():
        assert sorted(free) == list(range(g.slots)), rung
    # the queue is open again after the drain
    assert sched.submit(seq(20)) is None
    finals = {r["req_id"]: r for _, r in _run_to_completion(sched)
              if not r.get("partial")}
    assert finals[20]["ok"]
    assert g.slots_active() == 0


@pytest.mark.slow
def test_scheduler_flood_soak_slots_never_leak():
    """Churn soak: 60 mixed-size generations pushed through 2 slots
    and a bound-8 queue, re-submitting every shed until admitted, a
    third of them carrying tight deadlines.  Every admitted request
    gets EXACTLY one terminal reply (final, truncated, or deadline
    partial), and the pool ends whole."""
    from znicz_tpu.serving.batcher import GenSeq, GenerationScheduler

    wf = _charlm_wf(seq_len=32)
    g = _gen_runner(wf, cache_rungs=(8, 16, 32), slots=2,
                    prompt_rungs=(8,))
    sched = GenerationScheduler(g, max_new_cap=24, pending_bound=8)
    rng = np.random.default_rng(29)
    todo = [GenSeq(rng.integers(1, VOCAB,
                                size=int(rng.integers(2, 8))
                                ).astype(np.uint8),
                   int(rng.integers(1, 20)), req_id=1000 + i,
                   deadline_s=(0.05 if i % 3 == 0 else None))
            for i in range(60)]
    terminal: dict = {}
    sheds = 0
    while todo or sched.work_available():
        while todo:
            ref = sched.submit(todo[0])
            if ref is not None:
                assert ref.policy == "shed"
                sheds += 1
                break                        # queue full — go step
            todo.pop(0)
        _, reps = sched.step()
        for _, r in reps:
            if r.get("partial"):
                continue
            assert r["req_id"] not in terminal, "duplicate terminal"
            terminal[r["req_id"]] = r
    assert len(terminal) == 60
    assert sheds > 0                         # the bound actually bit
    assert any(r.get("timed_out") for r in terminal.values())
    assert any(r.get("ok") for r in terminal.values())
    assert g.slots_active() == 0
    for rung, free in g._free.items():
        assert sorted(free) == list(range(g.slots)), rung


# -- e2e service --------------------------------------------------------------


def test_e2e_generate_service(_generate_config):
    """The ``generate`` request kind end-to-end: greedy + seeded
    determinism over the wire, streamed partials, refusals naming the
    config knob, neighbor invisibility, truncation, stats export, and
    jit-cache hygiene over a repeated mixed stream."""
    from znicz_tpu.serving import InferenceClient, InferenceServer
    from znicz_tpu.serving.client import InferenceError

    wf = _charlm_wf(seq_len=32)
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=1.0,
                          warmup=False).start()
    cli = InferenceClient(srv.endpoint, timeout=60)
    rng = np.random.default_rng(23)
    try:
        prompt = rng.integers(1, VOCAB, size=5).astype(np.uint8)
        # greedy determinism over the wire
        a = cli.generate(prompt, max_new_tokens=6)
        b = cli.generate(prompt, max_new_tokens=6)
        assert a["prompt_len"] == 5 and len(a["tokens"]) == 6
        np.testing.assert_array_equal(a["tokens"], b["tokens"])
        # seeded sampling determinism
        s1 = cli.generate(prompt, 6, temperature=0.9, top_k=8, seed=37)
        s2 = cli.generate(prompt, 6, temperature=0.9, top_k=8, seed=37)
        np.testing.assert_array_equal(s1["tokens"], s2["tokens"])
        # streamed partials arrive in order and match the final
        got = []
        rid = cli.submit_generate(prompt, 6, stream=True,
                                  on_token=lambda t, i: got.append((i, t)))
        fin = cli.result(rid)
        assert [i for i, _ in got] == list(range(6))
        np.testing.assert_array_equal([t for _, t in got], fin["tokens"])
        # neighbor invisibility: the greedy probe co-batched with
        # sampled neighbors answers exactly like it did solo
        rid_p = cli.submit_generate(prompt, 6)
        rids = [cli.submit_generate(
                    rng.integers(1, VOCAB, size=4).astype(np.uint8), 6,
                    temperature=1.1, seed=100 + k) for k in range(2)]
        reps = {r: cli.result(r) for r in [rid_p] + rids}
        np.testing.assert_array_equal(reps[rid_p]["tokens"], a["tokens"])
        # refusals name the knob / ladder; service stays up
        with pytest.raises(InferenceError, match="prompt"):
            cli.generate(np.ones(33, np.uint8), 4)
        with pytest.raises(InferenceError,
                           match="generate.max_new_tokens"):
            cli.generate(prompt, 10 ** 6)
        # ladder-top truncation is a readable finish, not an error
        t = cli.generate(prompt, 40)
        assert t.get("truncated") and len(t["tokens"]) < 40
        # stats + telemetry surface
        st = srv.stats()["generate"]
        assert st["gen_finished"] >= 8 and st["slots_active"] == 0
        assert st["generated_tokens"] >= 8 * 6
        assert st["migrations"] >= 1      # the truncated run climbed rungs
        assert st["inter_token_p99_ms"] is not None
        # jit-cache hygiene: the same mixed stream again compiles NOTHING
        warm = srv.runner.compiles
        cache = srv.gen_sched.gen.jit_cache_size()
        cli.generate(prompt, 6)
        cli.generate(prompt, 6, temperature=0.9, top_k=8, seed=37)
        cli.generate(prompt, 40)
        assert srv.runner.compiles == warm
        assert srv.gen_sched.gen.jit_cache_size() in (None, cache)
    finally:
        cli.close()
        srv.stop()


def test_generate_disabled_is_refused_readably():
    from znicz_tpu.serving import InferenceClient, InferenceServer
    from znicz_tpu.serving.client import InferenceError

    root.common.serving.seq.rungs = [8, 32]
    try:
        wf = _charlm_wf(seq_len=32)
        srv = InferenceServer(wf, max_batch=4, max_delay_ms=1.0,
                              warmup=False).start()
        cli = InferenceClient(srv.endpoint, timeout=30)
        try:
            with pytest.raises(InferenceError,
                               match="generate.*enabled|enabled.*generate"):
                cli.generate(np.ones(3, np.uint8), 4)
        finally:
            cli.close()
            srv.stop()
    finally:
        root.common.serving.seq.rungs = None


def test_web_status_generation_row(_generate_config):
    from znicz_tpu.serving import InferenceClient, InferenceServer
    from znicz_tpu.web_status import WebStatus

    wf = _charlm_wf(seq_len=32)
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=1.0,
                          warmup=False).start()
    status = WebStatus(port=0).start()
    cli = InferenceClient(srv.endpoint, timeout=30)
    try:
        status.register(wf)
        status.register_inference(srv)
        cli.generate(np.ones(5, np.uint8), 6)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/status.json") as r:
            snap = json.load(r)
        gen = snap["serving"]["generate"]
        assert gen["gen_finished"] >= 1
        assert gen["generated_tokens"] >= 6
        assert gen["cache_rungs"] == [8, 16, 32]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/") as r:
            page = r.read().decode()
        assert "generation" in page and "KV slots" in page
    finally:
        cli.close()
        status.stop()
        srv.stop()


@pytest.mark.slow
def test_generate_chaos_soak(_generate_config):
    """Generations through a ChaosProxy (drop/corrupt/dup/delay both
    ways): every request eventually answers, resends of in-flight
    generations are deduplicated (never re-executed), greedy streams
    stay deterministic, and nothing recompiles after the first pass."""
    from znicz_tpu.parallel.chaos import ChaosProxy, FaultSchedule
    from znicz_tpu.serving import InferenceClient, InferenceServer

    wf = _charlm_wf(seq_len=32)
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=1.0,
                          warmup=False).start()
    schedule = FaultSchedule(seed=77, drop=0.08, corrupt=0.05,
                             duplicate=0.08, delay=0.05,
                             delay_s=(0.005, 0.03))
    front = "tcp://127.0.0.1:17699"
    proxy = ChaosProxy(front, srv.endpoint, schedule)
    proxy.start()
    cli = InferenceClient(front, timeout=120,
                          resend_after_s=0.3, breaker_failures=0)
    rng = np.random.default_rng(29)
    try:
        # clean-path references (direct, pre-chaos traffic shapes)
        ref_cli = InferenceClient(srv.endpoint, timeout=60)
        prompts = [rng.integers(1, VOCAB, size=int(rng.integers(2, 8))
                                ).astype(np.uint8) for _ in range(12)]
        want = [ref_cli.generate(p, 8)["tokens"] for p in prompts]
        ref_cli.close()
        # concurrent chaos traffic co-batches deeper than the serial
        # reference pass — warm the full executable family so the
        # zero-recompile assert sees a complete baseline
        srv.gen_sched.gen.warmup()
        warm = srv.runner.compiles
        rids = [cli.submit_generate(p, 8) for p in prompts]
        got = {}
        deadline = time.time() + 90
        while len(got) < len(rids) and time.time() < deadline:
            for rep in cli.collect(0.05):
                if rep.get("ok") and not rep.get("partial"):
                    got[rep["req_id"]] = rep["tokens"]
        assert len(got) == len(rids), (len(got), len(rids))
        for rid, w in zip(rids, want):
            np.testing.assert_array_equal(got[rid], w)
        assert srv.runner.compiles == warm
        assert srv.gen_sched.gen.slots_active() == 0
    finally:
        cli.close()
        proxy.stop()
        srv.stop()
