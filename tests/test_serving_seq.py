"""Variable-length serving (ISSUE 15): the 2-D (batch x seq) bucket
ladder — construction/refusals, seq-rung coalescing in the batcher
(incl. the reach-past-head drain), pad_ratio accounting, the masked
0-ULP parity contract at the runner level, zero recompiles over a mixed
stream, the web panel's pad_ratio column, and a chaos soak (slow)."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from znicz_tpu.core.config import root
from znicz_tpu.serving.batcher import (BucketLadder, DynamicBatcher,
                                       Request)

VOCAB = 32


def _charlm_wf(seq_len=32):
    from znicz_tpu.core import prng
    from znicz_tpu.samples.charlm import CharLMWorkflow

    prng.reset(1013)
    root.charlm.loader.update({"n_train": 64, "n_valid": 16, "n_test": 0,
                               "seq_len": seq_len, "minibatch_size": 16})
    root.charlm.model.update({"vocab": VOCAB, "embed": 32, "heads": 2,
                              "ffn": 64})
    wf = CharLMWorkflow()
    wf.initialize(device=None)
    return wf


# -- ladder geometry ----------------------------------------------------------


def test_bucket_ladder_2d():
    lad = BucketLadder(8, max_len=64)
    assert lad.rungs == [1, 2, 4, 8]
    assert lad.seq_rungs == [1, 2, 4, 8, 16, 32, 64]
    assert lad.seq_bucket_for(1) == 1
    assert lad.seq_bucket_for(9) == 16
    assert lad.seq_bucket_for(64) == 64
    assert len(lad.buckets()) == 4 * 7
    assert lad.bucket_key(4, 16) == "4x16"
    assert lad.bucket_key(4) == 4
    with pytest.raises(ValueError, match="top seq rung"):
        lad.seq_bucket_for(65)
    # explicit seq rungs must end at max_len
    lad2 = BucketLadder(8, max_len=64, seq_rungs=(8, 64))
    assert lad2.seq_rungs == [8, 64]
    with pytest.raises(ValueError, match="end", ):
        BucketLadder(8, max_len=64, seq_rungs=(8, 32))
    # seq rungs without a max_len make no sense
    with pytest.raises(ValueError, match="max_len"):
        BucketLadder(8, seq_rungs=(8, 64))
    # 1-D ladders are untouched: no seq axis anywhere
    lad1 = BucketLadder(8)
    assert lad1.seq_rungs is None
    assert lad1.buckets() == [1, 2, 4, 8]
    with pytest.raises(ValueError, match="no seq axis"):
        lad1.seq_bucket_for(3)


# -- batcher: seq-rung coalescing + pad accounting ----------------------------


def _req(n, L, client=None):
    x = np.ones((n, L), np.uint8)
    return Request(x, n, client=client, seq_len=L)


def test_batcher_coalesces_same_seq_rung_only():
    """Requests only share a batch with same-seq-rung neighbors, and the
    drain reaches PAST a mismatched-rung head instead of fragmenting
    (head-of-line blocking measured 0.76x goodput before the fix)."""
    b = DynamicBatcher(max_batch=8, max_delay_ms=1.0,
                       ladder=BucketLadder(8, max_len=64))
    for n, L in ((2, 5), (1, 20), (2, 7), (1, 60), (2, 8)):
        assert b.submit(_req(n, L)) is None
    first = b.next_batch(timeout=0.5)
    # rung 8: lengths 5, 7, 8 coalesce (the len-20/60 requests are
    # reached past, FIFO kept within the rung)
    assert [r.seq_len for r in first] == [5, 7, 8]
    second = b.next_batch(timeout=0.5)
    assert [r.seq_len for r in second] == [20]
    third = b.next_batch(timeout=0.5)
    assert [r.seq_len for r in third] == [60]
    # per-bucket accounting: 6 rows -> rows rung 8, seq rung 8
    hits = {k: v for k, v in b.bucket_hits.items() if v}
    assert hits == {"8x8": 1, "1x32": 1, "1x64": 1}
    # pad_ratio: batch 1 area 8*8=64, real 2*5+2*7+2*8=40
    assert b.pad_ratio()["8x8"] == round((64 - 40) / 40, 4)
    assert b.real_cells == 40 + 20 + 60
    assert b.padded_cells == (64 - 40) + (32 - 20) + (64 - 60)


def test_batcher_seq_oversize_refused_readably():
    b = DynamicBatcher(max_batch=8, max_delay_ms=1.0,
                       ladder=BucketLadder(8, max_len=64))
    reason = b.submit(_req(1, 65))
    assert reason is not None and reason.policy == "oversized"
    assert "65" in str(reason)
    assert b.oversized == 1


def test_batcher_seq_fairness_preserved():
    """The DRR discipline is untouched by the seq axis: two clients'
    same-rung requests interleave by deficit, and a mismatched-rung
    client simply waits for its own batch."""
    b = DynamicBatcher(max_batch=4, max_delay_ms=1.0,
                       ladder=BucketLadder(4, max_len=64))
    for i in range(3):
        assert b.submit(_req(1, 8, client="a")) is None
    assert b.submit(_req(1, 50, client="b")) is None
    batch = b.next_batch(timeout=0.5)
    assert [r.seq_len for r in batch] == [8, 8, 8]
    batch2 = b.next_batch(timeout=0.5)
    assert [r.seq_len for r in batch2] == [50]


# -- runner: 2-D warmup + masked 0-ULP parity ---------------------------------


def test_runner_2d_warmup_and_masked_parity():
    """Every (rows, seq) bucket compiles exactly once at warmup; within
    one bucket executable, a request's rows are a bit-exact pure
    function of its OWN rows and OWN length — garbage in every pad cell
    (its own tail AND neighbor rows) included."""
    from znicz_tpu.serving.model import ModelRunner

    wf = _charlm_wf(seq_len=32)
    runner = ModelRunner(wf)
    lad = BucketLadder(4, max_len=32, seq_rungs=(8, 32))
    assert runner.warmup(lad) == len(lad.buckets()) == 3 * 2
    c0 = runner.compiles

    rng = np.random.default_rng(11)
    probe = rng.integers(1, VOCAB, size=(2, 5)).astype(np.uint8)

    def run_bucket(neighbor, pad_value):
        """probe rows first, ``neighbor`` rows after, pads filled with
        ``pad_value`` — the (4, 8) bucket executable."""
        x = np.full((4, 8), pad_value, np.uint8)
        x[:2, :5] = probe
        x[2:2 + neighbor.shape[0], :neighbor.shape[1]] = neighbor
        return runner.infer(x)[:2, :5]

    base = run_bucket(rng.integers(1, VOCAB, size=(2, 7)
                                   ).astype(np.uint8), 0)
    for trial in range(3):
        neighbor = rng.integers(1, VOCAB, size=(2, 6 + trial)
                                ).astype(np.uint8)
        got = run_bucket(neighbor, pad_value=(VOCAB - 1) if trial else 0)
        np.testing.assert_array_equal(
            base, got,
            err_msg="probe rows changed with co-batched neighbor "
                    "content/length or pad garbage (masked 0-ULP)")
    assert runner.compiles == c0       # the stream was all cache hits


def test_runner_causal_pad_tail_invisible():
    """The causal mask IS the per-request padding mask on the LM: a
    request padded to a longer seq rung answers its real positions
    within numerical band of the exact-length compute (different
    executable — the PR 4/12 per-executable 0-ULP rule applies, so
    cross-rung agreement is a band, not bytes)."""
    from znicz_tpu.serving.model import ModelRunner

    wf = _charlm_wf(seq_len=32)
    runner = ModelRunner(wf)
    rng = np.random.default_rng(13)
    x = rng.integers(1, VOCAB, size=(1, 8)).astype(np.uint8)
    exact = runner.infer(x)[:, :8]
    padded = np.zeros((1, 32), np.uint8)
    padded[:, :8] = x
    via_pad = runner.infer(padded)[:, :8]
    np.testing.assert_allclose(via_pad, exact, rtol=1e-5, atol=1e-6)


# -- e2e service --------------------------------------------------------------


def test_e2e_seq_service_mixed_lengths():
    """Mixed-length stream end-to-end: per-length reply shapes, zero
    recompiles after warmup, pad_ratio/padded_cells exported through
    stats, per-request latency histograms keyed by rows rung intact."""
    from znicz_tpu.serving import InferenceClient, InferenceServer

    wf = _charlm_wf(seq_len=32)
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=2.0).start()
    cli = InferenceClient(srv.endpoint, timeout=60)
    try:
        assert srv.batcher.ladder.seq_rungs is not None
        warm = srv.runner.compiles
        assert warm == len(srv.batcher.ladder.buckets())
        rng = np.random.default_rng(17)
        for L in (1, 4, 9, 17, 32, 2, 31):
            y = cli.infer(rng.integers(1, VOCAB, size=(2, L)
                                       ).astype(np.uint8))
            assert y.shape == (2, L, VOCAB), (L, y.shape)
        # a bare (L,) sample means one row of L tokens in seq mode
        y = cli.infer(rng.integers(1, VOCAB, size=(7,)).astype(np.uint8))
        assert y.shape == (1, 7, VOCAB)
        assert srv.runner.compiles == warm
        assert srv.runner.jit_cache_size() in (None, warm)
        stats = srv.batcher.stats()
        assert stats["seq_rungs"] == srv.batcher.ladder.seq_rungs
        assert stats["real_cells"] > 0 and stats["pad_ratio"]
        # an over-long request is refused readably, service stays up
        from znicz_tpu.serving.client import InferenceError

        with pytest.raises(InferenceError, match="oversized|seq"):
            cli.result(cli.submit(
                rng.integers(1, VOCAB, size=(1, 33)).astype(np.uint8)))
        assert cli.infer(rng.integers(1, VOCAB, size=(1, 3)
                                      ).astype(np.uint8)).shape \
            == (1, 3, VOCAB)
    finally:
        cli.close()
        srv.stop()


def test_seq_serving_refuses_non_causal_attention():
    """A non-causal attention unit would hand PAD keys probability
    mass (replies become a function of the co-batched rung) — seq-mode
    serving refuses it at startup instead of answering wrong."""
    from znicz_tpu.serving import InferenceServer

    wf = _charlm_wf(seq_len=32)
    mha = next(f for f in wf.forwards if f.name == "mha")
    mha.causal = False
    with pytest.raises(ValueError, match="causal"):
        InferenceServer(wf)


def test_web_status_seq_panel_pad_ratio_column():
    from znicz_tpu.serving import InferenceClient, InferenceServer
    from znicz_tpu.web_status import WebStatus

    wf = _charlm_wf(seq_len=32)
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=2.0).start()
    status = WebStatus(port=0).start()
    cli = InferenceClient(srv.endpoint, timeout=30)
    try:
        status.register(wf)
        status.register_inference(srv)
        cli.infer(np.ones((2, 5), np.uint8))
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/status.json") as r:
            snap = json.load(r)
        b = snap["serving"]["batcher"]
        assert b["seq_rungs"] == [1, 2, 4, 8, 16, 32]
        assert b["real_cells"] >= 10
        assert isinstance(b["pad_ratio"], dict) and b["pad_ratio"]
        # JSON keys survive verbatim ("RxS" strings, not tuples)
        assert all(isinstance(k, str) and "x" in k
                   for k in b["bucket_hits"])
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/") as r:
            page = r.read().decode()
        assert "pad_ratio" in page and "seq rungs" in page
    finally:
        cli.close()
        status.stop()
        srv.stop()


@pytest.mark.slow
def test_seq_chaos_soak():
    """Slow soak (ISSUE 15 satellite): a mixed-length stream through a
    ChaosProxy (drop/corrupt/dup/delay both directions) — every request
    eventually answered bit-exactly per its own (rows, length), zero
    recompiles, bad frames counted not fatal."""
    from znicz_tpu.parallel.chaos import ChaosProxy, FaultSchedule
    from znicz_tpu.serving import InferenceClient, InferenceServer

    wf = _charlm_wf(seq_len=32)
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=2.0).start()
    schedule = FaultSchedule(seed=77, drop=0.08, corrupt=0.05,
                             duplicate=0.08, delay=0.05,
                             delay_s=(0.005, 0.03))
    front = "tcp://127.0.0.1:17698"
    proxy = ChaosProxy(front, srv.endpoint, schedule)
    proxy.start()
    cli = InferenceClient(front, timeout=120,
                          resend_after_s=0.5, breaker_failures=0)
    rng = np.random.default_rng(19)
    try:
        warm = srv.runner.compiles
        want = {}
        for i in range(60):
            L = int(rng.integers(1, 33))
            x = rng.integers(1, VOCAB, size=(1, L)).astype(np.uint8)
            want[cli.submit(x)] = x
        got = {}
        deadline = time.time() + 90
        while len(got) < len(want) and time.time() < deadline:
            for rep in cli.collect(0.05):
                if rep.get("ok"):
                    got[rep["req_id"]] = rep["y"]
        assert len(got) == len(want), (len(got), len(want))
        # every reply bit-exact vs the runner computing the request's
        # own bucket alone
        lad = srv.batcher.ladder
        for rid, x in want.items():
            L = x.shape[1]
            xb = np.zeros((lad.bucket_for(1), lad.seq_bucket_for(L)),
                          np.uint8)
            xb[:1, :L] = x
            np.testing.assert_array_equal(
                got[rid], srv.runner.infer(xb)[:1, :L])
        assert srv.runner.compiles == warm
    finally:
        cli.close()
        proxy.stop()
        srv.stop()
