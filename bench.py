"""Benchmark harness: AlexNet fused-train-step throughput on the attached
chip (BASELINE.md north-star metric).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Protocol (unsoftened AlexNet — VERDICT r1 item 3):
  - full 1000-class fc8 (the real AlexNet head);
  - 1024 resident training images (227x227x3) + 128 validation;
  - FRESH minibatch indices every step, drawn by driving the Loader state
    machine exactly like ``FusedTrainer.run`` does — the gather/input path
    varies per step and per epoch (reshuffle), nothing is cached;
  - a jax.profiler trace of 3 post-timing steps lands in ``bench_profile/``
    (best-effort: some remote platforms cannot trace).

``vs_baseline`` divides by 500 img/s — the widely published cuDNN-Caffe
AlexNet training throughput on a K40, standing in for the reference's own
number, which is unobtainable here (BASELINE.md: reference mount empty, no
network).  Update BASELINE.json.published when a real number lands.

``python bench.py --samples`` instead measures the BASELINE configs 0-3
finals (MNIST / CIFAR / MnistAE / Kohonen at their default sample configs)
and prints one JSON line per config — the numbers recorded in BASELINE.md's
"Measured" column.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

K40_ALEXNET_IMG_S = 500.0   # documented stand-in (see module docstring)

BATCH = 128
WARMUP = 3
STEPS = 20
N_TRAIN = 1024
N_VALID = 128
N_CLASSES = 1000
PROFILE_DIR = "bench_profile"


def main() -> None:
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root

    prng.seed_all(1013)
    root.common.engine.precision = "bfloat16"   # params fp32, MXU bf16
    root.alexnet.loader.minibatch_size = BATCH
    root.alexnet.loader.n_train = N_TRAIN
    root.alexnet.loader.n_valid = N_VALID
    root.alexnet.loader.n_classes = N_CLASSES
    root.alexnet.decision.max_epochs = 10_000   # bench drives steps itself

    import jax

    from znicz_tpu.loader.base import TRAIN
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples.alexnet import AlexNetWorkflow

    wf = AlexNetWorkflow()
    wf.initialize(device=None)
    trainer = FusedTrainer(wf)
    step = trainer.make_train_step()
    params = trainer.extract_params()
    vels = trainer.extract_velocities()
    dataset = wf.loader.original_data.devmem
    targets = wf.loader.original_labels.devmem
    hypers = trainer.hypers()

    def next_train_minibatch():
        """Advance the loader to its next TRAIN minibatch (fresh indices;
        epoch boundaries reshuffle, exactly as in training)."""
        while True:
            wf.loader.run()
            if wf.loader.minibatch_class == TRAIN:
                return (wf.loader.minibatch_indices.devmem,
                        np.int32(wf.loader.minibatch_size))

    def one_step(p, v, i):
        idx, bs = next_train_minibatch()
        return step(p, v, hypers, dataset, targets, idx, bs,
                    prng.get("bench").jax_key(i))

    for i in range(WARMUP):
        params, vels, metrics = one_step(params, vels, i)
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for i in range(STEPS):
        params, vels, metrics = one_step(params, vels, 100 + i)
    jax.block_until_ready(metrics)
    jax.block_until_ready(jax.tree_util.tree_leaves(params)[0])
    elapsed = time.perf_counter() - t0

    # post-timing profiler trace (never perturbs the measurement above)
    try:
        with jax.profiler.trace(PROFILE_DIR):
            for i in range(3):
                params, vels, metrics = one_step(params, vels, 1000 + i)
            jax.block_until_ready(metrics)
        print(f"profiler trace -> {PROFILE_DIR}/", file=sys.stderr)
    except Exception as exc:                      # platform can't trace
        print(f"profiler trace unavailable: {exc!r}", file=sys.stderr)

    img_s = BATCH * STEPS / elapsed
    print(json.dumps({
        "metric": "alexnet_imagenet_train_throughput",
        "value": round(img_s, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s / K40_ALEXNET_IMG_S, 3),
    }))


def _gd_finals(decision) -> dict:
    from znicz_tpu.loader.base import TRAIN, VALID

    return {"final_train_loss": round(decision.epoch_metrics[TRAIN]["loss"], 6),
            "valid_err_pct": round(decision.epoch_metrics[VALID]["err_pct"], 3),
            "epochs": int(decision.epoch_number) + 1}


def _mse_finals(decision) -> dict:
    from znicz_tpu.loader.base import TRAIN, VALID

    return {"final_train_mse": round(decision.epoch_metrics[TRAIN]["loss"], 6),
            "valid_mse": round(decision.epoch_metrics[VALID]["loss"], 6),
            "epochs": int(decision.epoch_number) + 1}


def _som_finals(decision) -> dict:
    return {"final_qerror": round(decision.epoch_qerror[-1], 6),
            "first_qerror": round(decision.epoch_qerror[0], 6),
            "epochs": len(decision.epoch_qerror)}


#: BASELINE config index -> (sample module name, finals extractor)
SAMPLE_CONFIGS = [
    (0, "mnist", _gd_finals),
    (1, "cifar", _gd_finals),
    (2, "mnist_ae", _mse_finals),
    (3, "kohonen", _som_finals),
]


def measure_samples() -> None:
    """BASELINE configs 0-3 at their default sample configs; one JSON line
    each (the BASELINE.md "Measured" column)."""
    import importlib

    from znicz_tpu.core import prng

    for config, name, finals in SAMPLE_CONFIGS:
        prng.reset(1013)
        module = importlib.import_module(f"znicz_tpu.samples.{name}")
        wf = module.run()
        print(json.dumps({"config": config, "sample": name,
                          **finals(wf.decision)}))


if __name__ == "__main__":
    if "--samples" in sys.argv[1:]:
        measure_samples()
    else:
        main()
