"""Benchmark harness: AlexNet fused-train-step throughput on the attached
chip (BASELINE.md north-star metric).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

``vs_baseline`` divides by 500 img/s — the widely published cuDNN-Caffe
AlexNet training throughput on a K40, standing in for the reference's own
number, which is unobtainable here (BASELINE.md: reference mount empty, no
network).  Update BASELINE.json.published when a real number lands.
"""

from __future__ import annotations

import json
import time

import numpy as np

K40_ALEXNET_IMG_S = 500.0   # documented stand-in (see module docstring)

BATCH = 128
WARMUP = 3
STEPS = 20


def main() -> None:
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root

    prng.seed_all(1013)
    root.common.engine.precision = "bfloat16"   # params fp32, MXU bf16
    root.alexnet.loader.minibatch_size = BATCH
    root.alexnet.loader.n_train = BATCH * 2
    root.alexnet.loader.n_valid = BATCH
    root.alexnet.loader.n_classes = 100
    root.alexnet.decision.max_epochs = 1

    import jax

    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples.alexnet import AlexNetWorkflow

    wf = AlexNetWorkflow()
    wf.initialize(device=None)
    trainer = FusedTrainer(wf)
    step = trainer.make_train_step()
    params = trainer.extract_params()
    vels = trainer.extract_velocities()
    dataset = wf.loader.original_data.devmem
    targets = wf.loader.original_labels.devmem
    wf.loader.run()
    while wf.loader.minibatch_class != 2:       # reach a TRAIN minibatch
        wf.loader.run()
    idx = wf.loader.minibatch_indices.devmem
    bs = np.int32(wf.loader.minibatch_size)

    hypers = trainer.hypers()
    for i in range(WARMUP):
        params, vels, metrics = step(params, vels, hypers, dataset, targets,
                                     idx, bs, prng.get("bench").jax_key(i))
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for i in range(STEPS):
        params, vels, metrics = step(params, vels, hypers, dataset, targets,
                                     idx, bs,
                                     prng.get("bench").jax_key(100 + i))
    jax.block_until_ready(metrics)
    jax.block_until_ready(jax.tree_util.tree_leaves(params)[0])
    elapsed = time.perf_counter() - t0

    img_s = BATCH * STEPS / elapsed
    print(json.dumps({
        "metric": "alexnet_imagenet_train_throughput",
        "value": round(img_s, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s / K40_ALEXNET_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
