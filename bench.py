"""Benchmark harness: AlexNet fused-train-step throughput on the attached
chip (BASELINE.md north-star metric).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Protocol (unsoftened AlexNet — VERDICT r1 item 3):
  - full 1000-class fc8 (the real AlexNet head);
  - 1024 resident training images (227x227x3) + 128 validation;
  - FRESH minibatch indices every step, drawn by driving the Loader state
    machine exactly like ``FusedTrainer.run`` does — the gather/input path
    varies per step and per epoch (reshuffle), nothing is cached;
  - the whole timed window is ONE ``lax.scan`` dispatch of STEPS train
    steps (the FusedTrainer's own scan path) — one executable launch, so
    the number measures device math, not per-dispatch link latency; the
    headline is the MEDIAN of three independently-timed windows
    (``elapsed_s_runs`` records all three);
  - a jax.profiler trace of a post-timing scan lands in ``bench_profile/``
    (best-effort: some remote platforms cannot trace).

``vs_baseline`` divides by 500 img/s — the widely published cuDNN-Caffe
AlexNet training throughput on a K40, standing in for the reference's own
number, which is unobtainable here (BASELINE.md: reference mount empty, no
network).  Update BASELINE.json.published when a real number lands.

Timing barrier: the timed window ends by PULLING VALUES to the host (last
loss + one element of every updated param) rather than
``jax.block_until_ready`` — on the tunneled "axon" platform
block_until_ready returns before the device finishes, so the r1/r2 numbers
(64.6k/75.1k img/s) were dispatch-rate artifacts, ~4x above what the chip
can physically do (the r3 self-validation below caught this: they implied
211% MFU on a 197-TFLOP/s v5e; a chained-matmul probe confirmed
block_until_ready returns in ~0.2ms where the math needs >100ms).

Self-validation (VERDICT r2 item 1): the JSON line carries
``flops_per_step`` (analytic, from the built layer shapes — convention:
MACs x 2 for every conv/GEMM, backward = 2x forward for weighted layers,
i.e. train = 3x forward; elementwise/pool/LRN ops are not counted),
``xla_flops_per_step`` (XLA's own cost model for the compiled step, a
cross-check on the analytic number), ``tflops_per_sec``, ``mfu_vs_peak``
(against a bf16 peak table keyed on ``device_kind`` — ``null`` with
``peak_tflops: null`` when the chip is unknown), and ``loss_untrained`` /
``loss_first`` / ``loss_last``; the bench FAILS if any timed loss is
non-finite or the timed tail is not well below the untrained starting
loss (the tail alone may oscillate at convergence — STEPS steps over the
resident set is dozens of epochs).

``python bench.py --samples`` instead measures the BASELINE configs 0-3
finals (MNIST / CIFAR / MnistAE / Kohonen at their default sample configs)
and prints one JSON line per config — the numbers recorded in BASELINE.md's
"Measured" column.

``python bench.py --fused-elementwise`` runs the SAME headline protocol
with ``root.common.engine.fused_elementwise`` on — the conv1/conv2
bias+ReLU+LRN+maxpool block (and its backward) as one single-pass Pallas
kernel (znicz_tpu/pallas_fused_block.py).  The JSON line records the flag;
a with/without pair on the same host is the BASELINE.md "Fused elementwise
block" comparison.

``python bench.py --wire`` instead microbenchmarks the v3 comms codec
(znicz_tpu/parallel/wire.py) on an MNIST-shaped update payload: one JSON
line with bytes/update, encode+decode ms and ratio vs the v2
pickle wire, per wire dtype (f32/bf16/int8) plus the zlib'd params
broadcast — the wire-cost record that rides the trajectory files
alongside MFU (ISSUE 3).

``python bench.py --seq`` gates variable-length serving (ISSUE 15) in
one JSON line: the 2-D (batch x seq) bucket ladder vs a single-max-len
ladder on the charlm transformer under a skewed-short mixed-length
stream — goodput in REAL tokens/s (FAILS below 2x), warmup compiles ==
rungs x seq_rungs with zero recompiles over the stream, and a
bit-exact masked-parity probe co-batched with varying same-rung
neighbors.

``python bench.py --generate`` gates autoregressive generation serving
(ISSUE 16) in one JSON line: the prefill/decode KV-cache path with
continuous batching vs a naive re-prefill-per-token oracle driven over
the SAME server's scoring plane (FAILS below 10x tokens/s, with the
generation path's p99 inter-token latency no worse than the oracle's
per-token p99), a per-decoded-token bit-exactness probe (the probe's
logits streamed back BIT-IDENTICAL across co-batched rounds of varying
neighbor content, its tokens identical down to the solo run — each
token a pure function of its own prompt), and the zero-recompile proof
over the mixed prompt-length/generation-length stream (warmup compiles
== scoring buckets + the paged prefill/decode/copy executable family,
nothing after).

``python bench.py --prefix`` gates the paged-KV upgrades (ISSUE 19) in
one JSON line: a seeded shared-system-prompt stream must prefill <=
0.5x the prompt tokens of a prefix-cache-off run of the SAME stream
with bit-exact decoded outputs between the two; a long-prompt barrage
co-batched with paced decoders must hold the decoders' p99 inter-token
latency within 1.5x of the no-barrage band (chunked prefill bounds the
per-tick prefill work); on-device sampling must ship <= 1/64 of the
logits path's per-tick reply bytes with bit-identical greedy tokens;
and the whole mixed stream must recompile NOTHING, both jit caches
gated by strict equality.

``python bench.py --serve`` gates the dynamic-batching inference service
(znicz_tpu/serving/, ISSUE 4) in one JSON line: interleaved sequential-
batch-1 vs coalesced-saturation throughput (FAILS below 3x, measured
WITH admission control enabled), paced-load p99 vs 2x(max_delay +
in-stream measured batch service time), an interleaved admission-on/off
p50 overhead gate at the same operating point (FAILS above 2% — ISSUE
6), and a zero-recompiles-after-warmup proof over a mixed-size request
stream (bucket-ladder jit cache).  All gates are relative to same-host,
same-phase measurements, so they are TPU-independent.

``python bench.py --fleet`` gates the replica-fleet serving plane
(znicz_tpu/serving/balancer.py, ISSUE 12) in one JSON line: a
3-replica fleet behind the health-checked balancer under a seeded
kill-and-restart timetable must lose ZERO acknowledged requests
(ledger: accepted == replied + refused), keep goodput within band of a
fault-free window measured in the same process, complete a canary
rollover triggered MID-chaos with every reply's generation stamp
consistent with the wave, and auto-roll-back a forced
parity-regression canary with the fleet still serving the old
generation bit-exactly.

``python bench.py --shard`` gates pod-scale sharded serving
(znicz_tpu/serving/model.py mesh mode, ISSUE 13) on 8 virtual CPU
devices in one JSON line: per-device shard shapes exact (rows/dp on
every data-axis device, staged AND computed), zero recompiles across a
mixed-size stream on the dp-snapped ladder, per-rung parity vs the
single-device reference (tight numerical band — reduction tiling is
layout-dependent; 0 ULP batch-independence WITHIN each mesh), the
default 1x1 config byte-identical to single-device serving, and a
{data:4}-vs-{data:2,model:2} layout comparison (recorded; TPU protocol
in BASELINE.md).

``python bench.py --telemetry`` gates the unified telemetry layer
(znicz_tpu/telemetry/, ISSUE 5): interleaved enabled/disabled best-of
windows of the real fused training loop; FAILS if spans + hot-loop
metrics cost more than 2% per step.

``python bench.py --legacy`` re-runs the round-1 protocol (100-class head,
256 resident images, FIXED minibatch indices) so the two protocols can be
compared on the same host/build (ADVICE r2: the recorded r1 vs r2 numbers
came from different local runs and were not comparable).

``python bench.py --stream`` measures the streaming pipeline
(loader/streaming.py, VERDICT r3 item 1) in one JSON line with four parts:

  - ``value``: u8-HBM-resident throughput — the SAME scan protocol over a
    28x-tiled u8 dataset (28,672 images) whose **float32 form (17.7 GB)
    exceeds the chip's HBM**; it trains entirely from HBM because storage
    stays uint8 with the decode fused into the gather.  ``pct_of_resident``
    compares against a resident-f32 window timed in the same process —
    the ">=90% of resident" gate.
  - ``staged``: true host->device streaming — segments assembled on the
    host (native row gather) and shipped per dispatch, double-buffered by
    async dispatch.  Steady state obeys
    ``img/s = min(compute_img_s, H2D_bytes_per_s / bytes_per_sample)``;
    the JSON carries the MEASURED link bandwidth and the bandwidth needed
    to be compute-bound, so the number self-explains on hosts where the
    TPU hangs off a tunnel (this dev host: ~16 MB/s, link-bound by 100x)
    versus a real PCIe-attached TPU host (>=8 GB/s, compute-bound).
  - ``decode``: the file-fed route's third roofline term (VERDICT r4
    item 1) — measured JPEG decode+resize rate through the training
    gather path (ImageFileSource), serial AND with the decode pool
    (loader/ingest.py), over synthetic 256x256 JPEGs resized to the
    network input.  ``roofline_img_s_3term`` =
    ``min(compute, link_bw/bytes_per_sample, decode_pooled)`` — the
    steady-state rate an image-FILE-fed training run sustains on this
    host; ``decode_bound`` says whether decode is the binding term.
  - the tiled content repeats 1024 base images, so the loss-descent
    self-check stays valid; the gather/decode path sees the full 28,672-row
    array (physically 4.4 GB of HBM), which is what is being measured.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

K40_ALEXNET_IMG_S = 500.0   # documented stand-in (see module docstring)

#: VERDICT r5 item 7 floors: the headline protocol FAILS below this MFU
#: (silent perf regressions must fail the bench, not pass unnoticed).
#: Applies only where the peak is known (a recognized TPU) and only to the
#: unmodified headline — labeled variants (--batch/--master-bf16/
#: --fused-elementwise) report without the gate so a measured negative
#: can still be recorded.
MFU_FLOOR = 0.37
HEADLINE_GUARDS = True      # cleared by variant CLI flags in __main__

BATCH = 128
STEPS = 200     # one scan dispatch; long enough to amortize the final host
                # sync (~100ms on tunneled platforms) to ~1% of the window;
                # warmup is one full same-length scan (compile reuse)
N_TRAIN = 1024
N_VALID = 128
N_CLASSES = 1000
PROFILE_DIR = "bench_profile"

#: dense bf16 peak TFLOP/s per chip, keyed by substrings of
#: ``jax.devices()[0].device_kind`` (public spec-sheet numbers).  The first
#: matching row wins; no match -> peak unknown -> mfu_vs_peak is null.
PEAK_TFLOPS_BF16 = [
    (("v6",), 918.0),                  # v6e / Trillium
    (("v5", "lite"), 197.0),           # v5e ("TPU v5 lite")
    (("v5e",), 197.0),
    (("v5",), 459.0),                  # v5p
    (("v4",), 275.0),
    (("v3",), 123.0),
    (("v2",), 46.0),
]


def peak_tflops(device_kind: str):
    kind = device_kind.lower()
    for needles, peak in PEAK_TFLOPS_BF16:
        if all(n in kind for n in needles):
            return peak
    return None


def analytic_train_flops(workflow, batch: int) -> int:
    """Analytic flops for ONE train step of the built workflow, from the
    actual initialized layer shapes.  Convention (stated in the module
    docstring): 2 flops per MAC; backward = 2x forward for every weighted
    layer (one GEMM/conv for d_input, one for d_weights) -> train = 3x
    forward MACs x 2.  Elementwise/pool/LRN/loss flops are excluded (<1%
    for AlexNet-class nets)."""
    from znicz_tpu.all2all import All2All
    from znicz_tpu.conv import Conv

    fwd_macs = 0
    for f in workflow.forwards:
        if isinstance(f, Conv):
            b, oh, ow, k = f.output.shape
            c = f.input.shape[-1]
            fwd_macs += batch * oh * ow * k * f.ky * f.kx * c
        elif isinstance(f, All2All):
            out_n = f.output_samples_number
            in_n = int(np.prod(f.input.shape[1:]))
            fwd_macs += batch * out_n * in_n
    return int(fwd_macs * 2 * 3)


def xla_flops(step, *args):
    """XLA's own cost model for the compiled step (best-effort; None when
    the platform/jax version does not expose it)."""
    try:
        cost = step.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):    # older jax: one dict/device
            cost = cost[0]
        return int(cost["flops"]) if cost and "flops" in cost else None
    except Exception as exc:
        print(f"xla cost_analysis unavailable: {exc!r}", file=sys.stderr)
        return None


def _build_bench_workflow(legacy: bool = False):
    """The bench's AlexNet workflow + FusedTrainer (shared by the headline
    and --stream protocols)."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root

    prng.seed_all(1013)
    root.common.engine.precision = "bfloat16"   # params fp32, MXU bf16
    # velocities stored bf16 (r4): halves optimizer-state HBM traffic in
    # the fc update fusions; update math stays f32 and the semantics are
    # parity-tested (tests/test_fused.py bf16_state_dtype cases)
    root.common.engine.state_dtype = "bfloat16"
    root.alexnet.loader.minibatch_size = BATCH
    root.alexnet.loader.n_train = 2 * BATCH if legacy else N_TRAIN
    root.alexnet.loader.n_valid = BATCH if legacy else N_VALID
    root.alexnet.loader.n_classes = 100 if legacy else N_CLASSES
    root.alexnet.decision.max_epochs = 10_000   # bench drives steps itself

    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples.alexnet import AlexNetWorkflow

    wf = AlexNetWorkflow()
    wf.initialize(device=None)
    return wf, FusedTrainer(wf)


def _make_materialize():
    """Build the materialize closure: forces REAL completion by pulling
    VALUES to the host in one fused transfer (axon's block_until_ready
    lies — see module docstring)."""
    import jax

    @jax.jit
    def probe(params, losses):
        import jax.numpy as jnp

        vals = [jnp.sum(losses).astype(jnp.float32)]
        for layer in params.values():
            for arr in layer.values():
                vals.append(arr[(0,) * arr.ndim].astype(jnp.float32))
        return jnp.stack(vals)

    def materialize(params, losses):
        return float(np.asarray(probe(params, losses))[0])

    return materialize


def main(legacy: bool = False) -> None:
    from znicz_tpu.core import prng

    import jax

    from znicz_tpu.loader.base import TRAIN

    wf, trainer = _build_bench_workflow(legacy)
    scan = trainer.make_train_scan()
    params = trainer.extract_params()
    vels = trainer.extract_velocities()
    dataset = wf.loader.original_data.devmem
    targets = wf.loader.original_labels.devmem
    # the scan takes per-step hypers rows (LR-schedule support);
    # the bench uses constant hypers
    hypers_mat = trainer.tiled_hypers(STEPS)

    wf.loader.indices_only = True     # the scan gathers on device itself

    def draw_minibatches(n):
        """n fresh TRAIN minibatches from the loader state machine (epoch
        boundaries reshuffle, exactly as in training) -> stacked index
        matrix + batch sizes.  ``legacy`` freezes the first minibatch
        (the r1 protocol's fixed-indices softening)."""
        idx, bs = [], []
        while len(idx) < n:
            wf.loader.run()
            if wf.loader.minibatch_class == TRAIN:
                idx.append(np.array(wf.loader.minibatch_indices.mem,
                                    np.int32))
                bs.append(wf.loader.minibatch_size)
        if legacy:
            idx = [idx[0]] * n
            bs = [bs[0]] * n
        return np.stack(idx), np.asarray(bs, np.int32)

    base_key = prng.get("bench").jax_base_key()

    def steps_from(start):
        return np.arange(start, start + STEPS, dtype=np.int32)

    materialize = _make_materialize()

    flops_step = analytic_train_flops(wf, BATCH)
    # warmup at the SAME scan length so the timed call reuses the compile
    idx_mat, bs_vec = draw_minibatches(STEPS)
    params, vels, ms, _conf = scan(params, vels, hypers_mat, dataset, targets,
                            idx_mat[:, :], bs_vec, base_key, steps_from(0))
    materialize(params, ms[0])
    warmup_losses = [float(l) for l in np.asarray(ms[0])]
    # XLA's cost model counts the scan (while-loop) body ONCE, so the
    # lowered scan's flops ARE the per-step flops
    xla_flops_step = xla_flops(
        scan, params, vels, hypers_mat, dataset, targets, idx_mat, bs_vec,
        base_key, steps_from(0))

    # three independently-timed windows, each restarted from the SAME
    # post-warmup state (device copies; the timed scans donate the
    # copies).  Restarting matters: letting the windows keep training
    # (800+ steps over 1024 resident images) drives the net into
    # bf16-overflow territory — the bench's own NaN check caught that.
    # The MEDIAN is the headline — robust to a one-off host/tunnel hiccup.
    import jax.numpy as jnp

    base_params = jax.tree_util.tree_map(jnp.copy, params)
    base_vels = jax.tree_util.tree_map(jnp.copy, vels)
    runs = []
    losses_per_run = []
    for r in range(3):
        idx_mat, bs_vec = draw_minibatches(STEPS)
        p = jax.tree_util.tree_map(jnp.copy, base_params)
        v = jax.tree_util.tree_map(jnp.copy, base_vels)
        t0 = time.perf_counter()        # ~1ms of copies may drain in-queue
        p, v, ms, _conf = scan(p, v, hypers_mat, dataset, targets,
                        idx_mat, bs_vec, base_key, steps_from(STEPS))
        materialize(p, ms[0])
        runs.append(time.perf_counter() - t0)
        losses_per_run.append(ms[0])
    elapsed = float(np.median(runs))
    ms = (losses_per_run[int(np.argsort(runs)[1])],)

    # the timed window must be REAL training: every loss finite, and the
    # trajectory (warmup start -> timed tail) clearly descending.  The tail
    # alone may sit on a converged plateau (STEPS steps over N_TRAIN
    # resident images = dozens of epochs), so the decrease is asserted
    # against the untrained starting loss, with margin.
    losses = [float(l) for l in np.asarray(ms[0])]
    assert all(np.isfinite(l) for l in losses), f"non-finite loss: {losses}"
    tail = float(np.mean(losses[-10:]))
    assert tail < 0.5 * warmup_losses[0], (
        f"training did not progress: start {warmup_losses[0]:.4f} -> "
        f"timed tail mean {tail:.4f}")

    # post-timing profiler trace (never perturbs the measurement above)
    try:
        with jax.profiler.trace(PROFILE_DIR):
            params, vels, ms, _conf = scan(params, vels, hypers_mat, dataset, targets,
                                    idx_mat, bs_vec, base_key,
                                    steps_from(3000))
            materialize(params, ms[0])
        print(f"profiler trace -> {PROFILE_DIR}/", file=sys.stderr)
    except Exception as exc:                      # platform can't trace
        print(f"profiler trace unavailable: {exc!r}", file=sys.stderr)

    img_s = BATCH * STEPS / elapsed
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "unknown")
    peak = peak_tflops(kind)
    tflops = flops_step * STEPS / elapsed / 1e12
    from znicz_tpu.core.config import root as _root

    print(json.dumps({
        "metric": ("alexnet_imagenet_train_throughput_legacy_r1_protocol"
                   if legacy else
                   "alexnet_imagenet_train_throughput" +
                   ("" if BATCH == 128 else f"_batch{BATCH}_variant")),
        "value": round(img_s, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s / K40_ALEXNET_IMG_S, 3),
        "batch": BATCH, "steps": STEPS, "elapsed_s": round(elapsed, 4),
        "elapsed_s_runs": [round(r, 4) for r in runs],
        "flops_per_step": flops_step,
        "xla_flops_per_step": xla_flops_step,
        "flops_convention": "2*MACs, train=3x fwd, conv+GEMM only",
        "tflops_per_sec": round(tflops, 2),
        "device_kind": kind,
        "platform": getattr(dev, "platform", "unknown"),
        "peak_tflops_bf16": peak,
        "mfu_vs_peak": round(tflops / peak, 4) if peak else None,
        "mfu_floor": MFU_FLOOR if (peak and not legacy and HEADLINE_GUARDS)
        else None,
        "fused_elementwise": bool(
            _root.common.engine.get("fused_elementwise", False)),
        "fused_tail": bool(_root.common.engine.get("fused_tail", False)),
        "compute_dtype": str(trainer.compute_dtype),
        "loss_untrained": round(warmup_losses[0], 4),
        "loss_first": round(losses[0], 4),
        "loss_last": round(losses[-1], 4),
    }))
    # VERDICT r5 item 7 floors, enforced AFTER the JSON line so a tripped
    # guard never destroys the measurement record it complains about (the
    # protocol explicitly wants negatives recorded), and via raise (not
    # assert) so ``python -O`` cannot strip the gate.
    if not trainer.compute_confusion:
        raise SystemExit(
            "confusion accumulation must stay ON in the bench protocol "
            "(the fused path sums it on device — bench.py measures that "
            "cost)")
    if peak and not legacy and HEADLINE_GUARDS:
        mfu = tflops / peak
        if mfu < MFU_FLOOR:
            raise SystemExit(
                f"headline MFU {mfu:.4f} fell below the {MFU_FLOOR} floor "
                f"on {kind} — a silent perf regression; investigate "
                "before re-recording (BASELINE.md ratchet)")


#: --product: min seconds between on-best snapshot saves (see the inline
#: comment at the assignment site)
SNAPSHOT_MIN_INTERVAL_S = 90.0


def product_main(epochs: int = 40) -> None:
    """``--product``: the PRODUCT path's throughput — ``FusedTrainer.run``
    driving the real AlexNetWorkflow (loader state machine, Decision,
    snapshotter gating, LR plumbing) at the bench protocol scale, NOT the
    raw scan (VERDICT r3 item 2: 'the hot loop IS the product').

    Two sync profiles measured in one process, BOTH with the snapshotter
    ACTIVE (gated on improvement, saving to a tmp dir — r5: the async
    writer serves it without stalling either path; VERDICT r4 item 4):
      - ``deep``: pipeline_depth>1 — whole epochs dispatched ahead, one
        fused metric pull per pipeline_depth epochs, snapshots written
        at flush boundaries by the background worker;
      - ``segmented``: default per-segment sync, snapshots handed to the
        same worker at epoch ends.

    ``warm_img_per_sec`` (compile-excluded, from the trainer's own stats)
    is the comparable number; the JSON also carries the wall total and
    the snapshot-writer counters (written / coalesced)."""
    import tempfile

    from znicz_tpu.core.config import root as _root

    results = {}
    for mode in ("deep", "segmented"):
        _root.common.engine.scan_chunk = 16
        _root.common.engine.pipeline_depth = 8 if mode == "deep" else 1
        wf, trainer = _build_bench_workflow()
        n_epochs = epochs if mode == "deep" else max(8, epochs // 2)
        _root.alexnet.decision.max_epochs = n_epochs
        wf.decision.max_epochs = n_epochs
        snap_dir = tempfile.mkdtemp(prefix="bench_snap_")
        wf.snapshotter.directory = snap_dir
        wf.snapshotter.compression = "raw"    # gzip of 300 MB would
        # dominate the writer's wall time on one core
        # each on-best save pulls the full ~300 MB param+velocity set
        # device->host; on this tunneled link (~20 MB/s) that is ~15 s of
        # SHARED link occupancy which stalls the training loop's own
        # transfers — rate-limit best-saves like an operator would (a
        # PCIe-attached host would run with 0)
        wf.snapshotter.min_save_interval_s = SNAPSHOT_MIN_INTERVAL_S
        t0 = time.time()
        try:
            trainer.run()
            snapshots_on_disk = len(os.listdir(snap_dir))
        finally:
            import shutil

            shutil.rmtree(snap_dir, ignore_errors=True)
        stats = dict(trainer.stats)
        results[mode] = {
            "warm_img_per_sec": stats["warm_img_per_sec"],
            "img_per_sec_incl_compile": stats["img_per_sec"],
            "train_steps": stats["train_steps"],
            "epochs": n_epochs,
            "wall_s": round(time.time() - t0, 2),
            "pipeline_depth": trainer.pipeline_depth,
            "scan_chunk": trainer.scan_chunk,
            "final_train_loss": round(
                wf.decision.epoch_metrics[2]["loss"], 4),
            "snapshots_written": wf.snapshotter.async_saves_written,
            "snapshots_coalesced": wf.snapshotter.async_saves_coalesced,
            "snapshots_on_disk": snapshots_on_disk,
        }
        assert np.isfinite(results[mode]["final_train_loss"])
        # r4 weak #3 closure gates: the fast (deep) configuration now
        # checkpoints, and the segmented+snapshotter mode is no longer
        # collapsed by the writeback+pickle stall
        assert results[mode]["snapshots_written"] > 0, mode
    print(json.dumps({
        "metric": "alexnet_product_path_train_throughput",
        "value": results["deep"]["warm_img_per_sec"],
        "unit": "images/sec/chip",
        "vs_baseline": round(
            results["deep"]["warm_img_per_sec"] / K40_ALEXNET_IMG_S, 3),
        "epochs": epochs, "batch": BATCH,
        "snapshot_min_interval_s": SNAPSHOT_MIN_INTERVAL_S,
        "deep": results["deep"],
        "segmented_with_snapshotter": results["segmented"],
    }))


#: --stream protocol knobs
N_STREAM_TILE = 28     # 28 * 1024 = 28,672 u8 images in HBM; their f32
                       # form (28,672 * 618 KB = 17.7 GB) EXCEEDS v5e HBM
N_HOST_TILE = 8        # host-staged dataset: 8,192 u8 images (1.27 GB RAM)
STAGE_CHUNK = 8        # train steps per staged segment (1024 samples)
STAGE_SEGMENTS = 3     # timed staged segments
N_DECODE_JPG = 192     # synthetic JPEGs for the decode-rate term
N_DECODE_MEASURE = 128  # rows decoded per timed decode window
CHECK_LOSS = True      # False only for tiny-shape smoke runs (tests)


def stream_main() -> None:
    """The --stream protocol (module docstring): u8-HBM-residency at
    beyond-f32-HBM dataset scale, plus true host->device staging with a
    measured link-bandwidth roofline."""
    from znicz_tpu.core import prng

    import jax
    import jax.numpy as jnp

    wf, trainer = _build_bench_workflow()
    scan = trainer.make_train_scan()
    materialize = _make_materialize()
    loader = wf.loader
    dataset_f32 = loader.original_data.devmem
    labels_dev = loader.original_labels.devmem
    base_key = prng.get("bench").jax_base_key()
    rng = np.random.default_rng(1013)

    def draw_idx(n_steps, n_total):
        """Epoch-shuffled minibatch index rows over [0, n_total)."""
        out, perm = [], np.array([], np.int32)
        while len(out) < n_steps:
            if len(perm) < BATCH:
                perm = rng.permutation(n_total).astype(np.int32)
            out.append(perm[:BATCH])
            perm = perm[BATCH:]
        return np.stack(out)

    def copies(tree):
        return jax.tree_util.tree_map(jnp.copy, tree)

    hypers = trainer.tiled_hypers(STEPS)
    bs_vec = np.full(STEPS, BATCH, np.int32)
    steps0 = np.arange(STEPS, dtype=np.int32)
    # data layout is [test | valid | train] (AlexNetLoader), so TRAIN rows
    # start after the eval split — all protocols sample the train region,
    # exactly like main()'s loader-driven indices
    n_eval = int(dataset_f32.shape[0]) - N_TRAIN

    # ---- warmup + resident-f32 reference window (the main protocol) ------
    params, vels = trainer.extract_params(), trainer.extract_velocities()
    params, vels, ms, _ = scan(params, vels, hypers, dataset_f32,
                               labels_dev,
                               n_eval + draw_idx(STEPS, N_TRAIN),
                               bs_vec, base_key, steps0)
    materialize(params, ms[0])
    loss_untrained = float(np.asarray(ms[0])[0])
    base_params, base_vels = copies(params), copies(vels)
    t0 = time.perf_counter()
    p, v, ms, _ = scan(copies(base_params), copies(base_vels), hypers,
                       dataset_f32, labels_dev,
                       n_eval + draw_idx(STEPS, N_TRAIN),
                       bs_vec, base_key, steps0 + STEPS)
    materialize(p, ms[0])
    resident_img_s = BATCH * STEPS / (time.perf_counter() - t0)

    # ---- u8-resident: tiled u8 dataset whose f32 form exceeds HBM --------
    lo = float(jnp.min(dataset_f32))
    hi = float(jnp.max(dataset_f32))
    scale = np.float32((hi - lo) / 255.0)
    shift = np.float32(lo)
    trainer._decode_params = (scale, shift)   # read at (re)trace for u8

    @jax.jit
    def quantize_tile(d, l):
        # tile the TRAIN region only — every index into the tiled array
        # is then a train row
        u8 = jnp.clip(jnp.round((d[n_eval:] - shift) / scale),
                      0, 255).astype(jnp.uint8)
        return (jnp.tile(u8, (N_STREAM_TILE, 1, 1, 1)),
                jnp.tile(l[n_eval:], (N_STREAM_TILE,)))

    big_u8, big_labels = quantize_tile(dataset_f32, labels_dev)
    n_big = N_TRAIN * N_STREAM_TILE
    dataset_f32_gb = n_big * int(np.prod(dataset_f32.shape[1:])) * 4 / 2**30
    dataset_u8_gb = dataset_f32_gb / 4
    # compile for the u8 dtype/shape, then median-of-3 timed windows
    p, v, ms, _ = scan(copies(base_params), copies(base_vels), hypers,
                       big_u8, big_labels, draw_idx(STEPS, n_big), bs_vec,
                       base_key, steps0)
    materialize(p, ms[0])
    runs, losses_per_run = [], []
    for _ in range(3):
        idx = draw_idx(STEPS, n_big)
        p, v = copies(base_params), copies(base_vels)
        t0 = time.perf_counter()
        p, v, ms, _ = scan(p, v, hypers, big_u8, big_labels, idx, bs_vec,
                           base_key, steps0 + STEPS)
        materialize(p, ms[0])
        runs.append(time.perf_counter() - t0)
        losses_per_run.append([float(x) for x in np.asarray(ms[0])])
    u8_elapsed = float(np.median(runs))
    u8_img_s = BATCH * STEPS / u8_elapsed
    losses = losses_per_run[int(np.argsort(runs)[1])]
    assert all(np.isfinite(x) for x in losses), losses
    tail = float(np.mean(losses[-10:]))
    # CHECK_LOSS False is for tiny-shape smoke runs only (a handful of
    # steps cannot halve the loss); the real protocol always asserts
    assert not CHECK_LOSS or tail < 0.5 * loss_untrained, \
        (loss_untrained, tail)
    del big_u8, big_labels, p, v

    # ---- host-staged streaming + link roofline ---------------------------
    host_f32 = loader.original_data.mem[n_eval:]     # train rows only
    host_u8_base = np.clip(np.round((host_f32 - shift) / scale),
                           0, 255).astype(np.uint8)
    host_u8 = np.tile(host_u8_base, (N_HOST_TILE, 1, 1, 1))
    host_labels = np.tile(np.asarray(
        loader.original_labels.mem[n_eval:], np.int32), N_HOST_TILE)
    n_host = len(host_u8)
    bytes_per_sample = int(np.prod(host_u8.shape[1:]))

    # measured link bandwidth: one timed 64 MB u8 put, value-materialized
    probe_buf = host_u8.reshape(-1)[:64 << 20]
    x = jax.device_put(probe_buf)
    float(jnp.sum(x[:: 1 << 20].astype(jnp.float32)))      # warm the path
    t0 = time.perf_counter()
    x = jax.device_put(probe_buf)
    float(jnp.sum(x[:: 1 << 20].astype(jnp.float32)))
    h2d_gbps = len(probe_buf) / (time.perf_counter() - t0) / 2**30

    seg_hypers = trainer.tiled_hypers(STAGE_CHUNK)
    seg_bs = np.full(STAGE_CHUNK, BATCH, np.int32)
    local_idx = np.arange(STAGE_CHUNK * BATCH, dtype=np.int32).reshape(
        STAGE_CHUNK, BATCH)

    def stage(flat):
        return (jax.device_put(np.take(host_u8, flat, axis=0)),
                jax.device_put(np.take(host_labels, flat)))

    def staged_window(p, v, n_segments, step0):
        for s in range(n_segments):
            flat = draw_idx(STAGE_CHUNK, n_host).reshape(-1)
            buf, lab = stage(flat)
            p, v, ms, _ = scan(p, v, seg_hypers, buf, lab, local_idx,
                               seg_bs, base_key,
                               np.arange(step0 + s * STAGE_CHUNK,
                                         step0 + (s + 1) * STAGE_CHUNK,
                                         dtype=np.int32))
        materialize(p, ms[0])
        return [float(x) for x in np.asarray(ms[0])]

    p, v = copies(base_params), copies(base_vels)
    staged_window(p, v, 1, 0)                    # compile the staged shape
    p, v = copies(base_params), copies(base_vels)
    t0 = time.perf_counter()
    staged_losses = staged_window(p, v, STAGE_SEGMENTS, STAGE_CHUNK)
    staged_s = time.perf_counter() - t0
    staged_img_s = BATCH * STAGE_CHUNK * STAGE_SEGMENTS / staged_s
    assert all(np.isfinite(x) for x in staged_losses), staged_losses

    # ---- decode rate: the roofline's third term (VERDICT r4 item 1) ------
    # A synthetic JPEG tree at ImageNet-ish geometry (256x256 source files
    # decoded+resized to the network's 227x227 input), measured through
    # the same ImageFileSource gather path training uses — serial and
    # with the decode pool (loader/ingest.py).
    import shutil
    import tempfile

    from PIL import Image

    from znicz_tpu.loader.ingest import measure_decode_rate
    from znicz_tpu.loader.streaming import ImageFileSource

    sample_hw = tuple(dataset_f32.shape[1:3])
    jpg_dir = tempfile.mkdtemp(prefix="znicz_bench_jpg_")
    try:
        n_jpg = N_DECODE_JPG
        img_rng = np.random.default_rng(7)
        paths = []
        for i in range(n_jpg):
            p = os.path.join(jpg_dir, f"{i}.jpg")
            Image.fromarray(img_rng.integers(
                0, 255, (256, 256, 3), dtype=np.uint8)).save(p, quality=85)
            paths.append(p)
        src = ImageFileSource(paths, np.zeros(n_jpg, np.int32),
                              target_shape=sample_hw, workers=0)
        decode_serial = measure_decode_rate(src, n=N_DECODE_MEASURE)
        pooled_src = ImageFileSource(paths, np.zeros(n_jpg, np.int32),
                                     target_shape=sample_hw)  # default pool
        decode_pooled = measure_decode_rate(pooled_src, n=N_DECODE_MEASURE)
        decode_workers = (pooled_src._pool.workers
                          if pooled_src._pool is not None else 1)
    finally:
        shutil.rmtree(jpg_dir, ignore_errors=True)

    needed_gbps = u8_img_s * bytes_per_sample / 2**30
    link_img_s = h2d_gbps * 2**30 / bytes_per_sample
    dev = jax.devices()[0]
    print(json.dumps({
        "metric": "alexnet_stream_train_throughput_u8_resident",
        "value": round(u8_img_s, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(u8_img_s / K40_ALEXNET_IMG_S, 3),
        "batch": BATCH, "steps": STEPS,
        "elapsed_s_runs": [round(r, 4) for r in runs],
        "dataset_images": n_big,
        "dataset_f32_gb": round(dataset_f32_gb, 2),
        "dataset_u8_gb": round(dataset_u8_gb, 2),
        "resident_f32_img_s": round(resident_img_s, 2),
        "pct_of_resident": round(100 * u8_img_s / resident_img_s, 1),
        "loss_untrained": round(loss_untrained, 4),
        "loss_last": round(losses[-1], 4),
        "staged": {
            "img_s": round(staged_img_s, 2),
            "images": BATCH * STAGE_CHUNK * STAGE_SEGMENTS,
            "host_dataset_images": n_host,
            "bytes_per_sample_u8": bytes_per_sample,
            "h2d_gbps_measured": round(h2d_gbps, 4),
            "h2d_gbps_for_compute_bound": round(needed_gbps, 3),
            "link_bound": bool(h2d_gbps < needed_gbps),
            "roofline_img_s_at_measured_bw": round(
                min(u8_img_s, link_img_s), 2),
        },
        "decode": {
            # file-fed route (ImageFileSource): JPEG decode+resize to the
            # network input, through the training gather path
            "img_s_serial": round(decode_serial, 2),
            "img_s_pooled": round(decode_pooled, 2),
            "workers": int(decode_workers),
            "pool_speedup": round(decode_pooled / max(decode_serial, 1e-9),
                                  2),
            # min(compute, link, decode): the steady-state rate an
            # image-FILE-fed training run can sustain on this host
            "roofline_img_s_3term": round(
                min(u8_img_s, link_img_s, decode_pooled), 2),
            "decode_bound": bool(decode_pooled < min(u8_img_s, link_img_s)),
        },
        "device_kind": getattr(dev, "device_kind", "unknown"),
    }))


#: --wire payload: the MNIST sample's trainable shapes (the same layer
#: set the tests' master/slave runs ship every update), repeated TILE
#: times so the codec is timed on a multi-MB payload, not cache noise
WIRE_LAYER_SHAPES = {"fc1": {"weights": (784, 100), "bias": (100,)},
                     "fc2": {"weights": (100, 10), "bias": (10,)}}
WIRE_TILE = 8
WIRE_REPS = 5


def wire_main() -> None:
    """``--wire``: comms-codec microbench.  Builds a synthetic update
    (seeded normal deltas at MNIST layer shapes x WIRE_TILE + metrics
    with a confusion matrix), measures encode+decode wall time and
    bytes-on-wire per wire dtype against the v2 single-pickle wire, and
    the zlib'd f32 params broadcast (the cold path).  Pure host-side —
    no accelerator, no sockets — so the JSON line isolates codec cost
    from transport and compute."""
    import pickle
    import time as _time

    from znicz_tpu.parallel import wire

    rng = np.random.default_rng(1013)
    deltas = {}
    for t in range(WIRE_TILE):
        for name, layer in WIRE_LAYER_SHAPES.items():
            deltas[f"{name}_t{t}"] = {
                k: (rng.normal(0, 0.01, shape) * 0.1).astype(np.float32)
                for k, shape in layer.items()}
    metrics = {"loss": 1.0, "n_err": 3,
               "confusion": rng.integers(0, 60, (10, 10))}
    raw_bytes = sum(a.nbytes for layer in deltas.values()
                    for a in layer.values())

    def timed(fn):
        best = float("inf")
        for _ in range(WIRE_REPS):
            t0 = _time.perf_counter()
            out = fn()
            best = min(best, _time.perf_counter() - t0)
        return out, best * 1e3          # min over reps, in ms

    def update_msg(enc_deltas):
        return {"cmd": "update", "id": "bench", "job_id": 1,
                "deltas": enc_deltas, "metrics": metrics}

    # the v2 baseline: one pickle blob of the raw f32 update
    blob, pickle_enc_ms = timed(
        lambda: pickle.dumps(update_msg(deltas),
                             pickle.HIGHEST_PROTOCOL))
    _, pickle_dec_ms = timed(lambda: pickle.loads(blob))
    v2_bytes = len(blob)

    results = {"pickle_v2": {
        "bytes_per_update": v2_bytes,
        "encode_ms": round(pickle_enc_ms, 3),
        "decode_ms": round(pickle_dec_ms, 3),
        "ratio_vs_pickle_v2": 1.0}}
    for dtype in ("float32", "bfloat16", "int8"):
        enc = wire.DeltaEncoder(dtype)

        def encode():
            frames, _ = wire.encode_message(update_msg(enc.encode(deltas)))
            return frames
        frames, enc_ms = timed(encode)
        frames = [bytes(f) for f in frames]     # what the peer receives
        (dec, _), dec_ms = timed(lambda: wire.decode_message(frames))
        n_bytes = sum(len(f) for f in frames)
        err = max(float(np.max(np.abs(dec["deltas"][name][k]
                                      - deltas[name][k])))
                  for name in deltas for k in deltas[name])
        results[dtype] = {
            "bytes_per_update": n_bytes,
            "encode_ms": round(enc_ms, 3),
            "decode_ms": round(dec_ms, 3),
            "ratio_vs_pickle_v2": round(v2_bytes / n_bytes, 3),
            "max_abs_err": float(f"{err:.3e}"),
        }

    # cold path: the f32 params broadcast, zlib'd (fresh-init weights
    # compress well; converged ones less — this records the mechanism)
    bcast = {"job_id": 1, "params": deltas}
    frames, enc_ms = timed(
        lambda: wire.encode_message(bcast, compress="zlib")[0])
    frames = [bytes(f) for f in frames]
    _, dec_ms = timed(lambda: wire.decode_message(frames))
    plain = sum((bytes(f).__len__())
                for f in wire.encode_message(bcast)[0])
    results["params_zlib"] = {
        "bytes": sum(len(f) for f in frames),
        "encode_ms": round(enc_ms, 3),
        "decode_ms": round(dec_ms, 3),
        "ratio_vs_raw": round(plain / sum(len(f) for f in frames), 3),
    }

    print(json.dumps({
        "metric": "wire_codec_bytes_per_update_int8",
        "value": results["int8"]["bytes_per_update"],
        "unit": "bytes",
        "vs_baseline": results["int8"]["ratio_vs_pickle_v2"],
        "payload_f32_mb": round(raw_bytes / 2**20, 3),
        "tensors": sum(len(v) for v in deltas.values()),
        "wire": results,
    }))
    # the acceptance floor (ISSUE 3): int8 must beat the pickle wire by
    # >= 3.5x on this payload; enforced AFTER the JSON line so a tripped
    # gate never destroys the measurement it complains about
    if results["int8"]["ratio_vs_pickle_v2"] < 3.5:
        raise SystemExit(
            f"int8 wire ratio {results['int8']['ratio_vs_pickle_v2']} "
            "fell below the 3.5x floor vs the v2 pickle wire")


#: --agg protocol knobs (ISSUE 10): the O(slaves) -> O(fanout) proof.
#: Phase 1 (structural, scripted): 8 protocol-exact scripted slaves run
#: the same seeded job/update stream once as a STAR (all 8 on the
#: master) and once through a fanout-2 RELAY TREE (8 -> 4 -> 2 ->
#: master); the master's wire.Codec counts bytes-into-master and
#: messages decoded.  Both must drop to <= 0.35x the star's — the ~4x
#: the two aggregated tiers owe.  Phase 2 (semantic, seeded MNIST): a
#: real 4-slave training once as a star and once through a 2-level
#: tree (2 leaf relays under 1 mid relay) must land in the same
#: converged band — error-feedback residuals held at the leaves AND
#: per-relay, so quantization behavior is unchanged.  Gates fire AFTER
#: the JSON line so a trip never destroys the measurement.
AGG_SLAVES = 8
AGG_FANOUT = 2
AGG_RATIO_CEIL = 0.35
AGG_CONV_BAND = 25.0        # |star - tree| err_pct tolerance (async
#                             replicas differ run to run regardless of
#                             topology; both must land converged)
AGG_ERR_CEIL = 70.0
AGG_BASE_PORT = 18600

#: --agg phase 3 (ISSUE 11): the ELASTIC scenario — the same 8-slave
#: fanout-2 tree with quorum + bounded/weighted staleness on, run once
#: fault-free and once with a seeded SubtreePreempter killing mid-relay
#: 0's WHOLE subtree (1 mid + 2 leaf relays + 4 slaves = half the
#: fleet, >= the 1/3 the acceptance demands) mid-run and restarting it
#: ~5 s later.  Gates: the preempted run lands inside the fault-free
#: band, apply progress CONTINUES during the kill window, and the job
#: ledger balances (jobs_done + requeues + refusals == dispatched — no
#: gradient lost or double-applied across the re-plan).  The denser
#: job stream needs a calmer lr: at the sample default 0.1, 8 fully-
#: async replicas over 20 minibatches/epoch diverge with or without
#: the elastic knobs.
ELASTIC_MIN_SLAVES = 3
ELASTIC_STALENESS_BOUND = 50
ELASTIC_LR = 0.03
ELASTIC_EPOCHS = 5
ELASTIC_N_TRAIN = 1200
ELASTIC_SEED = 23
ELASTIC_BAND = 25.0


def _agg_make_workflow(tag: str, max_epochs: int = 3,
                       n_train: int = 300):
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = n_train
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = max_epochs
    root.common.dirs.snapshots = f"/tmp/bench_agg/{tag}"
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def _agg_scripted_slave(endpoint: str, sid: str, register_msg: dict,
                        shapes: dict, errors: list) -> None:
    """A protocol-exact scripted slave: registers, pulls jobs, replies
    tiny constant deltas of the right shapes — all the wire traffic of
    a real slave with none of the compute, so the byte/decode counters
    measure TOPOLOGY, not this host's training speed."""
    import zmq

    from znicz_tpu.parallel import wire

    ctx = zmq.Context.instance()
    sock = ctx.socket(zmq.REQ)
    sock.setsockopt(zmq.RCVTIMEO, 60_000)
    sock.setsockopt(zmq.LINGER, 0)
    sock.connect(endpoint)

    def rpc(msg):
        frames, _ = wire.encode_message(dict(msg, id=sid))
        sock.send_multipart(frames)
        return wire.decode_message(sock.recv_multipart())[0]

    try:
        rep = rpc(register_msg)
        if not rep.get("ok"):
            raise RuntimeError(f"register refused: {rep.get('error')}")
        while True:
            rep = rpc({"cmd": "job"})
            if rep.get("done"):
                return
            if "job" not in rep:
                time.sleep(0.005)           # wait / transient
                continue
            job = rep["job"]
            deltas = None
            if rep.get("train"):
                deltas = {name: {k: np.full(shape, 1e-6, np.float32)
                                 for k, shape in layer.items()}
                          for name, layer in shapes.items()}
            if "minibatches" in job:
                metrics = [{"loss": 1.0, "n_err": 0}
                           for _ in job["minibatches"]]
            else:
                metrics = {"loss": 1.0, "n_err": 0}
            rpc({"cmd": "update", "job_id": rep["job_id"],
                 "deltas": deltas, "metrics": metrics})
    except Exception as exc:                # surface thread crashes
        errors.append((sid, repr(exc)))
        raise
    finally:
        sock.close(0)


def _agg_scripted_run(endpoints, master_endpoint, tag):
    """Drive AGG_SLAVES scripted slaves against ``endpoints[i]`` (the
    star: all the master; the tree: their leaf relays); returns the
    master server after completion."""
    import threading

    from znicz_tpu.network_common import handshake_request
    from znicz_tpu.server import Server

    # plentiful jobs (30 TRAIN minibatches/epoch for 8 slaves) so the
    # stream stays dense enough for pairs to FORM at every tier — the
    # regime the tree exists for; a trickle would measure idle polling
    wf = _agg_make_workflow(f"{tag}_m", max_epochs=2, n_train=1800)
    server = Server(wf, endpoint=master_endpoint, job_timeout=60.0)
    register = handshake_request(wf)
    shapes = {f.name: {k: tuple(a.shape) for k, a in f.params().items()}
              for f in wf.forwards if f.has_weights}
    errors: list = []
    threads = [threading.Thread(
        target=_agg_scripted_slave,
        args=(endpoints[i], f"{tag}{i}", register, shapes, errors),
        daemon=True) for i in range(AGG_SLAVES)]
    for t in threads:
        t.start()
    server.serve()
    for t in threads:
        t.join(timeout=60)
    if errors:
        raise SystemExit(f"scripted slaves crashed: {errors}")
    if any(t.is_alive() for t in threads):
        raise SystemExit("scripted slaves hung")
    if not bool(wf.decision.complete):
        raise SystemExit("scripted run did not complete")
    return server


def _agg_real_fleet(endpoints, master_endpoint, tag):
    """A real seeded 4-slave MNIST training over whatever topology sits
    between ``endpoints`` and the master; returns (server, err_pct)."""
    import threading

    from znicz_tpu.client import Client
    from znicz_tpu.server import Server

    wf = _agg_make_workflow(f"{tag}_m")
    server = Server(wf, endpoint=master_endpoint, job_timeout=60.0)
    slaves = [Client(_agg_make_workflow(f"{tag}_s{i}"),
                     endpoint=endpoints[i], slave_id=f"{tag}w{i}")
              for i in range(len(endpoints))]
    errors: list = []

    def worker(s):
        try:
            s.run()
        except BaseException as e:
            errors.append((s.slave_id, repr(e)))
            raise

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in slaves]
    for t in threads:
        t.start()
    server.serve()
    for t in threads:
        t.join(timeout=120)
    if errors:
        raise SystemExit(f"slaves crashed: {errors}")
    dec = wf.decision
    if not bool(dec.complete):
        raise SystemExit(f"{tag}: training did not complete")
    return server, float(dec.epoch_metrics[1]["err_pct"])


def _agg_elastic_run(tag, port, preempt: bool):
    """One elastic 8-slave fanout-2 tree run (ISSUE 11): quorum +
    bounded/weighted staleness on; with ``preempt``, a seeded
    :class:`SubtreePreempter` kills mid-relay 0's whole subtree
    mid-run and restarts it.  Returns ``(server, err_pct, marks)`` —
    ``marks`` holds the counter snapshots taken at kill and restart,
    the degraded-window progress evidence."""
    import threading

    from znicz_tpu.client import Client
    from znicz_tpu.core.config import root
    from znicz_tpu.parallel.chaos import (FaultSchedule, RelayHarness,
                                          SubtreePreempter)
    from znicz_tpu.parallel.relay import plan_tree
    from znicz_tpu.server import Server

    master_ep = f"tcp://127.0.0.1:{port}"
    plan = plan_tree(AGG_SLAVES, AGG_FANOUT, master_ep,
                     base_port=port + 1)
    from znicz_tpu.samples import mnist  # noqa: F401 -- the import
    # applies the sample's config DEFAULTS; reading prev_lr before it
    # would capture None and the restore below would poison the tree
    prev_lr = root.mnist.get("learning_rate")
    root.mnist.learning_rate = ELASTIC_LR
    preempter = None
    harnesses = []
    try:
        wf = _agg_make_workflow(f"{tag}_m", max_epochs=ELASTIC_EPOCHS,
                                n_train=ELASTIC_N_TRAIN)
        # job_timeout is the reap CEILING and must sit well inside the
        # down window: the epoch tail waits on the dead subtree's
        # in-flight jobs, and only the reaper frees it
        server = Server(wf, endpoint=master_ep, job_timeout=2.5,
                        slave_ttl=1.5, min_slaves=ELASTIC_MIN_SLAVES,
                        staleness_bound=ELASTIC_STALENESS_BOUND,
                        staleness_weight=True)
        harnesses = [RelayHarness(r["upstream"], r["bind"],
                                  relay_id=f"{tag}-r{i}",
                                  recv_timeout=1.0, max_reconnects=60,
                                  child_ttl=1.5)
                     for i, r in enumerate(plan["relays"])]
        for h in harnesses:
            h.start()
        wfs = [_agg_make_workflow(f"{tag}_s{i}",
                                  max_epochs=ELASTIC_EPOCHS,
                                  n_train=ELASTIC_N_TRAIN)
               for i in range(AGG_SLAVES)]
        clients = [Client(wfs[i], endpoint=plan["slave_endpoints"][i],
                          slave_id=f"{tag}w{i}")
                   for i in range(AGG_SLAVES)]
        errors, threads = [], {}

        def start_slave(i):
            def worker(c):
                try:
                    c.run(recv_timeout=1.0, max_reconnects=80,
                          backoff_base=0.05, backoff_cap=0.4,
                          connect_retries=80)
                except BaseException as e:
                    errors.append((c.slave_id, repr(e)))
                    raise
            t = threading.Thread(target=worker, args=(clients[i],),
                                 daemon=True)
            threads[i] = t
            t.start()

        for i in range(AGG_SLAVES):
            start_slave(i)
        marks = {}
        server_thread = threading.Thread(
            target=server.serve, kwargs={"linger": 6.0}, daemon=True)
        server_thread.start()
        if preempt:
            mid_bind = plan["relays"][0]["bind"]
            sub_relays = [0] + [j for j, r in enumerate(plan["relays"])
                                if r["upstream"] == mid_bind]
            sub_binds = {plan["relays"][j]["bind"] for j in sub_relays}
            sub_slaves = [i for i, ep
                          in enumerate(plan["slave_endpoints"])
                          if ep in sub_binds]

            def snap():
                return {"jobs_done": int(server.jobs_done),
                        "aggregated": int(server.aggregated_updates),
                        "weighted": int(server.weighted_applies),
                        "members": int(server.member_count())}

            def kill():
                for i in sub_slaves:
                    clients[i].preempt()
                for i in sub_slaves:
                    threads[i].join(timeout=10)
                for j in sub_relays:
                    harnesses[j].kill(timeout=10)
                marks["kill"] = snap()

            def restart():
                marks["restart"] = snap()
                for j in sub_relays:
                    harnesses[j].start()
                for i in sub_slaves:
                    clients[i] = Client(
                        wfs[i], endpoint=plan["slave_endpoints"][i],
                        slave_id=f"{tag}w{i}")
                    start_slave(i)

            marks["preempted"] = {"relays": len(sub_relays),
                                  "slaves": len(sub_slaves)}
            preempter = SubtreePreempter(
                FaultSchedule(ELASTIC_SEED),
                [("mid0-subtree", kill, restart)],
                kill_s=(0.2, 0.6), down_s=(4.5, 5.5))
            deadline = time.time() + 180
            while server.jobs_done < 12 and time.time() < deadline \
                    and server_thread.is_alive():
                time.sleep(0.05)
            if server.jobs_done < 12 or not server_thread.is_alive():
                # a dead/stalled warm-up must fail AS a warm-up
                # failure, not fire the kill anyway and trip the
                # progress gate with a misleading message
                raise SystemExit(
                    f"{tag}: warm-up failed before the preemption "
                    f"(jobs_done={server.jobs_done}, master alive="
                    f"{server_thread.is_alive()}) — enlarge the "
                    "workload or the deadline")
            preempter.start()       # seeded timetable, anchored mid-run
        server_thread.join(timeout=600)
        if server_thread.is_alive():
            raise SystemExit(f"{tag}: master hung")
        if preempter is not None and not preempter.join(60):
            raise SystemExit(f"{tag}: preempter hung")
        for t in threads.values():
            t.join(timeout=60)
        if errors:
            raise SystemExit(f"{tag}: slaves crashed: {errors}")
        if any(t.is_alive() for t in threads.values()):
            raise SystemExit(f"{tag}: slaves hung")
        dec = wf.decision
        if not bool(dec.complete):
            raise SystemExit(f"{tag}: training did not complete")
        return server, float(dec.epoch_metrics[1]["err_pct"]), marks
    finally:
        root.mnist.learning_rate = prev_lr
        if preempter is not None:
            preempter.stop()
        for h in harnesses:
            try:
                h.kill(timeout=5)
            except Exception:
                pass


def agg_main() -> None:
    """``--agg``: the relay-tree aggregation gate (ISSUE 10).  One JSON
    line with the star-vs-tree byte/decode ratios and the convergence
    band; FAILS (after printing) when bytes-into-master or the master's
    decode count at fanout 2 with 8 scripted slaves exceeds 0.35x the
    star's, or when the tree's seeded MNIST run leaves the star's
    convergence band."""
    from znicz_tpu.parallel.relay import Relay, plan_tree

    port = AGG_BASE_PORT

    # -- phase 1: scripted star ------------------------------------------------
    star_master = f"tcp://127.0.0.1:{port}"
    star = _agg_scripted_run([star_master] * AGG_SLAVES, star_master,
                             "star")
    star_bytes = int(star.bytes_in)
    star_decodes = int(star.codec.messages_in)

    # -- phase 1: scripted fanout-2 tree (8 -> 4 -> 2 -> master) ---------------
    tree_master = f"tcp://127.0.0.1:{port + 1}"
    plan = plan_tree(AGG_SLAVES, AGG_FANOUT, tree_master,
                     base_port=port + 2)
    relays = [Relay(r["upstream"], r["bind"], relay_id=f"agg-r{i}",
                    fanout=AGG_FANOUT).start()
              for i, r in enumerate(plan["relays"])]
    try:
        tree = _agg_scripted_run(plan["slave_endpoints"], tree_master,
                                 "tree")
    finally:
        for r in relays:
            r.stop()
    tree_bytes = int(tree.bytes_in)
    tree_decodes = int(tree.codec.messages_in)
    bytes_ratio = tree_bytes / max(1, star_bytes)
    decode_ratio = tree_decodes / max(1, star_decodes)

    # -- phase 2: seeded MNIST convergence, star vs 2-level tree ---------------
    conv_star_master = f"tcp://127.0.0.1:{port + 20}"
    srv_star, err_star = _agg_real_fleet(
        [conv_star_master] * 4, conv_star_master, "cstar")
    conv_tree_master = f"tcp://127.0.0.1:{port + 21}"
    mid = f"tcp://127.0.0.1:{port + 22}"
    leaf_a = f"tcp://127.0.0.1:{port + 23}"
    leaf_b = f"tcp://127.0.0.1:{port + 24}"
    relays = [Relay(conv_tree_master, mid, relay_id="agg-mid").start(),
              Relay(mid, leaf_a, relay_id="agg-leaf-a").start(),
              Relay(mid, leaf_b, relay_id="agg-leaf-b").start()]
    try:
        srv_tree, err_tree = _agg_real_fleet(
            [leaf_a, leaf_a, leaf_b, leaf_b], conv_tree_master, "ctree")
    finally:
        for r in relays:
            r.stop()

    # -- phase 3: the elastic scenario (ISSUE 11) ------------------------------
    srv_ff, err_ff, _ = _agg_elastic_run("eff", port + 40, preempt=False)
    srv_pre, err_pre, marks = _agg_elastic_run("epre", port + 60,
                                               preempt=True)
    ledger = srv_pre.jobs_ledger()

    print(json.dumps({
        "metric": "agg_bytes_into_master_ratio",
        "value": round(bytes_ratio, 4),
        "unit": "tree/star",
        "vs_baseline": round(1.0 / max(bytes_ratio, 1e-9), 2),
        "slaves": AGG_SLAVES, "fanout": AGG_FANOUT,
        "star": {"bytes_in": star_bytes, "decodes": star_decodes,
                 "jobs_done": star.jobs_done,
                 "updates": star.updates_received},
        "tree": {"bytes_in": tree_bytes, "decodes": tree_decodes,
                 "jobs_done": tree.jobs_done,
                 "updates": tree.updates_received,
                 "aggregated": tree.aggregated_updates,
                 "levels": plan["levels"]},
        "decode_ratio": round(decode_ratio, 4),
        "convergence": {"star_err_pct": err_star,
                        "tree_err_pct": err_tree,
                        "tree_aggregated":
                            srv_tree.aggregated_updates,
                        "star_aggregated":
                            srv_star.aggregated_updates},
        "elastic": {
            "fault_free_err_pct": err_ff,
            "preempted_err_pct": err_pre,
            "min_slaves": ELASTIC_MIN_SLAVES,
            "staleness_bound": ELASTIC_STALENESS_BOUND,
            "preempted": marks.get("preempted"),
            "kill": marks.get("kill"), "restart": marks.get("restart"),
            "stale_refused": srv_pre.stale_refused,
            "weighted_applies": srv_pre.weighted_applies,
            "replans": srv_pre.replans,
            "preemptions_ridden": srv_pre.preemptions_ridden,
            "reregistrations": srv_pre.reregistrations,
            "ledger": ledger,
        },
    }))
    # gates AFTER the JSON line (ISSUE 10 acceptance)
    if bytes_ratio > AGG_RATIO_CEIL:
        raise SystemExit(
            f"bytes-into-master ratio {bytes_ratio:.3f} exceeds the "
            f"{AGG_RATIO_CEIL} ceiling (star {star_bytes}, tree "
            f"{tree_bytes})")
    if decode_ratio > AGG_RATIO_CEIL:
        raise SystemExit(
            f"master decode-count ratio {decode_ratio:.3f} exceeds the "
            f"{AGG_RATIO_CEIL} ceiling (star {star_decodes}, tree "
            f"{tree_decodes})")
    if err_star >= AGG_ERR_CEIL or err_tree >= AGG_ERR_CEIL:
        raise SystemExit(
            f"convergence left the band: star {err_star}%, tree "
            f"{err_tree}% (ceiling {AGG_ERR_CEIL}%)")
    if abs(err_star - err_tree) >= AGG_CONV_BAND:
        raise SystemExit(
            f"star-vs-tree convergence gap {abs(err_star - err_tree):.1f}"
            f" exceeds the {AGG_CONV_BAND}-point band "
            f"(star {err_star}%, tree {err_tree}%)")
    if srv_tree.aggregated_updates <= 0 or tree.aggregated_updates <= 0:
        raise SystemExit("tree runs produced no aggregated updates — "
                         "the relays were not in the path")
    # -- elastic gates (ISSUE 11 acceptance) -----------------------------------
    if err_pre >= AGG_ERR_CEIL or err_ff >= AGG_ERR_CEIL:
        raise SystemExit(
            f"elastic convergence left the band: fault-free {err_ff}%, "
            f"preempted {err_pre}% (ceiling {AGG_ERR_CEIL}%)")
    if abs(err_pre - err_ff) >= ELASTIC_BAND:
        raise SystemExit(
            f"preempted run left the fault-free band: "
            f"|{err_pre} - {err_ff}| >= {ELASTIC_BAND}")
    k, r = marks.get("kill"), marks.get("restart")
    if not k or not r:
        raise SystemExit("the preemption never executed — no kill/"
                         "restart marks recorded")
    if r["jobs_done"] <= k["jobs_done"]:
        raise SystemExit(
            f"no apply progress during the kill window: jobs_done "
            f"{k['jobs_done']} -> {r['jobs_done']}")
    if r["aggregated"] <= k["aggregated"] and \
            r["weighted"] <= k["weighted"]:
        raise SystemExit(
            "no aggregated/weighted applies during the kill window: "
            f"{k} -> {r}")
    if not ledger["balanced"]:
        raise SystemExit(
            f"job ledger does not balance after the re-plan — a job "
            f"was lost or double-counted: {ledger}")
    if srv_pre.preemptions_ridden < 1 or srv_pre.replans < 1:
        raise SystemExit(
            "the elastic machinery never engaged: preemptions_ridden="
            f"{srv_pre.preemptions_ridden}, replans={srv_pre.replans}")
    if srv_pre.weighted_applies <= 0:
        raise SystemExit("no staleness-weighted applies in a fully-"
                         "async 8-slave run — the stamps are not "
                         "flowing")


#: --serve protocol knobs (ISSUE 4).  All gates are RELATIVE to numbers
#: measured on the same host in the same process, so they hold on this
#: TPU-less throttled-CPU container and transfer unchanged to a TPU
#: host.  The model is the MNIST MLP widened to 2048 so batch COMPUTE
#: genuinely dominates per-request codec/python overhead — the regime
#: dynamic batching exists for (a toy-thin model measures only
#: per-request overhead, which coalescing cannot amortize by design).
SERVE_MAX_BATCH = 32
SERVE_MAX_DELAY_MS = 20.0
SERVE_HIDDEN = 2048
SERVE_BASELINE_S = 2.0      # sequential batch-1 window
SERVE_LOAD_S = 3.0          # saturation (closed-loop) window
SERVE_PACED_S = 4.0         # paced-latency (open-loop) window
SERVE_MIXED_S = 1.5         # mixed-size recompile-proof window
SERVE_WINDOW = 2 * SERVE_MAX_BATCH   # closed-loop in-flight requests
SERVE_PACED_FRACTION = 0.7  # latency SLO operating point (of capacity;
#                             0.7 leaves headroom for this container's
#                             cgroup-share swings between the capacity
#                             measurement and the paced phase)
SERVE_LATENCY_ROUNDS = 3    # best-of rounds (shared-host load spikes)
SERVE_THROUGHPUT_FLOOR = 3.0
SERVE_P99_MULT = 2.0
#: admission/deadline overhead gate (ISSUE 6): interleaved
#: admission-ON/OFF paced windows at the same 0.7x operating point,
#: best-of per variant (telemetry-gate discipline: a cgroup load spike
#: must hit both variants, and it can only ever slow a window down).
#: The ON policy is a generous rate limit + fair queueing: the full
#: token-bucket/DRR/deadline code path runs on every request without
#: refusing any (refusals would change the measured population).
SERVE_ADMISSION_S = 2.0     # paced window per variant per round
SERVE_ADMISSION_ROUNDS = 4  # bounded interleaved pairs, early-exit
SERVE_ADMISSION_PCT = 2.0   # p50 overhead ceiling, percent


def _build_serve_workflow():
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root

    prng.reset(1013)
    root.mnist.loader.n_train = 512
    root.mnist.loader.n_valid = 64
    root.mnist.loader.minibatch_size = 64
    root.mnist.layers = [SERVE_HIDDEN, 10]

    from znicz_tpu.samples import mnist

    try:
        wf = mnist.MnistWorkflow()
    finally:
        root.mnist.layers = [100, 10]
    wf.initialize(device=None)
    return wf


def serve_main() -> None:
    """``--serve``: the dynamic-batching inference gates (ISSUE 4), one
    JSON line.  Four phases against the SAME model on the same host:

      - sequential batch-1 baseline: a ``max_batch=1`` service driven
        one request at a time — the per-request service rate with no
        coalescing and no added delay;
      - saturation throughput: ``SERVE_WINDOW`` (= 2 x max_batch, the
        ping-pong design point: one full batch computing, one filling)
        single-row requests kept in flight CLOSED-LOOP — rows/s at
        offered load saturating max_batch (gate: >= 3x sequential);
      - paced latency: OPEN-LOOP arrivals at ``SERVE_PACED_FRACTION``
        of the measured capacity — the operating point a latency SLO is
        quoted at (closed-loop saturation latency is W/lambda, pure
        queueing; no service quotes its SLO at rho=1).  Gate: p99 <=
        2 x (max_delay_ms + batch_ms), where batch_ms is a full
        max_batch-row request's e2e service time measured at idle
        IMMEDIATELY before each round (this container's cgroup CPU
        share swings minute to minute — the bound must be measured
        under the conditions of the phase it bounds); best of
        ``SERVE_LATENCY_ROUNDS`` rounds, since a background load spike
        can only ever slow a round down;
      - mixed-size stream: request sizes sweep 1..max_batch while the
        compile counter is watched — the bucket ladder must absorb
        every shape (gate: ZERO recompiles after warmup, by the trace
        counter AND jax's own jit-cache size).

    Gates are enforced AFTER the JSON line so a tripped gate never
    destroys the measurement record it complains about."""
    import gc
    import time as _time

    from znicz_tpu.serving import (AdmissionPolicy, InferenceClient,
                                   InferenceServer)

    sys.setswitchinterval(1e-3)       # 3 busy threads on a shared core:
    # the default 5ms GIL slice adds multi-ms scheduling jitter straight
    # onto every latency quantile

    wf = _build_serve_workflow()
    sample_shape = tuple(int(d) for d in wf.forwards[0].input.shape[1:])
    rng = np.random.default_rng(1013)
    x1 = rng.normal(0, 1, (1,) + sample_shape).astype(np.float32)
    xb = rng.normal(0, 1, (SERVE_MAX_BATCH,) + sample_shape
                    ).astype(np.float32)

    # ---- both services up front: the sequential baseline and the
    # coalescing service are measured in INTERLEAVED windows (this
    # container's cgroup CPU share swings minute to minute — comparing
    # a quiet-moment baseline against a loaded-moment coalesced run
    # would make the RELATIVE gate noise, not signal; best-of windows
    # per service, since background load only ever slows a window down)
    # breaker OFF on both bench clients (breaker_failures=0): the
    # closed-loop phases deliberately overdrive the queue bound, and a
    # polite client backing off on shed would distort the very offered
    # load the saturation/shed behavior is measured under
    srv1 = InferenceServer(wf, max_batch=1, max_delay_ms=0.0).start()
    cli1 = InferenceClient(srv1.endpoint, timeout=120,
                           breaker_failures=0)
    # admission control ENABLED for every gated phase (ISSUE 6): the
    # rate limit is generous so nothing is refused, but every request
    # pays the token-bucket + fair-queue + deadline bookkeeping — the
    # coalescing and p99 gates must hold WITH the admission path on
    adm_on = AdmissionPolicy(rate_limit=1e9, rate_burst=1e9, fair=True)
    srv = InferenceServer(wf, max_batch=SERVE_MAX_BATCH,
                          max_delay_ms=SERVE_MAX_DELAY_MS,
                          queue_bound=8 * SERVE_MAX_BATCH,
                          admission=adm_on).start()
    compiles_warm = srv.runner.compiles   # every ladder rung compiled
    cli = InferenceClient(srv.endpoint, timeout=120, breaker_failures=0)

    submitted_at = {}

    def drive_closed(duration_s, sizes, lats=None):
        """Closed loop: keep SERVE_WINDOW requests in flight, cycling
        ``sizes`` rows per request; returns (rows, elapsed)."""
        rows = 0
        i = 0
        t0 = _time.perf_counter()
        while _time.perf_counter() - t0 < duration_s:
            while cli.in_flight < SERVE_WINDOW:
                nrow = sizes[i % len(sizes)]
                i += 1
                rid = cli.submit(x1 if nrow == 1 else np.repeat(
                    x1, nrow, axis=0))
                submitted_at[rid] = _time.perf_counter()
            for rep in cli.collect(0.002):
                t_rep = _time.perf_counter()
                t_sub = submitted_at.pop(rep["req_id"], None)
                if lats is not None and t_sub is not None:
                    lats.append(t_rep - t_sub)
                if rep.get("ok"):
                    rows += rep["y"].shape[0]
        elapsed = _time.perf_counter() - t0
        while cli.in_flight:              # drain the tail — NOT counted:
            for rep in cli.collect(0.01):  # rows finishing after
                submitted_at.pop(rep["req_id"], None)  # `elapsed` froze
                # would inflate the measured rate (the sequential
                # baseline has no such tail to inflate it with)
        return rows, elapsed

    def drive_paced(duration_s, rate_qps, probe_every_s=0.25):
        """Open loop: single-row arrivals paced at ``rate_qps``, with a
        full max_batch-row PROBE request injected every
        ``probe_every_s`` — its e2e RTT is the measured batch service
        time under the exact conditions the latency quantiles are
        measured under (this container's cgroup CPU share is bursty;
        an idle-time batch_ms can be 4x off by the time the phase
        runs).  Returns (single-row latencies, probe latencies),
        seconds."""
        lats = []
        probe_lats = []
        probe_ids = set()
        t0 = _time.perf_counter()
        i = 0
        next_probe = probe_every_s
        while _time.perf_counter() - t0 < duration_s:
            now = _time.perf_counter()
            if now - t0 >= next_probe:
                next_probe += probe_every_s
                rid = cli.submit(xb)
                probe_ids.add(rid)
                submitted_at[rid] = _time.perf_counter()
            elif now - t0 >= i / rate_qps and \
                    cli.in_flight < 4 * SERVE_MAX_BATCH:
                rid = cli.submit(x1)
                submitted_at[rid] = _time.perf_counter()
                i += 1
            for rep in cli.collect(0.001):
                t_rep = _time.perf_counter()
                rid = rep["req_id"]
                t_sub = submitted_at.pop(rid, None)
                if t_sub is None:
                    continue
                (probe_lats if rid in probe_ids else lats).append(
                    t_rep - t_sub)
                probe_ids.discard(rid)
        while cli.in_flight:
            for rep in cli.collect(0.01):
                t_rep = _time.perf_counter()
                rid = rep["req_id"]
                t_sub = submitted_at.pop(rid, None)
                if t_sub is None:
                    continue
                (probe_lats if rid in probe_ids else lats).append(
                    t_rep - t_sub)
                probe_ids.discard(rid)
        return lats, probe_lats

    # ---- phases 1+2, interleaved: sequential baseline vs saturation ------
    for _ in range(20):
        cli1.infer(x1)                    # warm the batch-1 request path
    seq_qps = 0.0
    coalesced_qps = 0.0
    for _ in range(3):
        t0 = _time.perf_counter()
        n = 0
        while _time.perf_counter() - t0 < SERVE_BASELINE_S / 3:
            cli1.infer(x1)
            n += 1
        seq_qps = max(seq_qps, n / (_time.perf_counter() - t0))
        rows, elapsed = drive_closed(SERVE_LOAD_S / 3, sizes=[1])
        coalesced_qps = max(coalesced_qps, rows / elapsed)
    cli1.close()
    srv1.stop()
    occupancy = srv.batcher.occupancy()

    # ---- phase 3: paced latency at the SLO operating point ---------------
    gc.collect()
    gc.freeze()                           # long-lived state out of gen
    gc.disable()                          # scans; no multi-ms GC pauses
    # on the latency quantiles (re-enabled after the phase)
    rounds = []
    try:
        for _ in range(SERVE_LATENCY_ROUNDS):
            lats, probe_lats = drive_paced(
                SERVE_PACED_S, SERVE_PACED_FRACTION * coalesced_qps)
            a = np.asarray(lats) * 1e3
            bms = float(np.median(np.asarray(probe_lats) * 1e3))
            rounds.append({
                "batch_ms": round(bms, 2),
                "p50_ms": round(float(np.percentile(a, 50)), 2),
                "p99_ms": round(float(np.percentile(a, 99)), 2),
                "p99_bound_ms": round(
                    SERVE_P99_MULT * (SERVE_MAX_DELAY_MS + bms), 2),
                "n": len(lats),
            })
            if rounds[-1]["p99_ms"] <= rounds[-1]["p99_bound_ms"]:
                break                     # gate met; no need to re-roll
    finally:
        gc.enable()
    best = min(rounds, key=lambda r: r["p99_ms"] - r["p99_bound_ms"])

    # ---- phase 3b: admission/deadline overhead (interleaved on/off) ------
    adm_off = AdmissionPolicy(enabled=False)
    on_p50: list = []
    off_p50: list = []
    gc.collect()
    gc.disable()
    try:
        for _ in range(SERVE_ADMISSION_ROUNDS):
            for policy, dest in ((adm_off, off_p50), (adm_on, on_p50)):
                srv.batcher.set_admission(policy)
                lats, _ = drive_paced(
                    SERVE_ADMISSION_S,
                    SERVE_PACED_FRACTION * coalesced_qps)
                dest.append(float(np.percentile(
                    np.asarray(lats) * 1e3, 50)))
            if min(on_p50) <= min(off_p50) * (
                    1 + SERVE_ADMISSION_PCT / 100):
                break                     # gate met; stop burning time
    finally:
        gc.enable()
        srv.batcher.set_admission(adm_on)
    admission_overhead_pct = (min(on_p50) / min(off_p50) - 1.0) * 100

    # ---- phase 4: mixed-size stream (bucket-ladder proof) ----------------
    drive_closed(SERVE_MIXED_S,
                 sizes=[1, 2, 3, 5, 8, 13, 21, SERVE_MAX_BATCH, 7, 2, 30])
    recompiles = srv.runner.compiles - compiles_warm
    jit_cache = srv.runner.jit_cache_size()
    stats = srv.stats()
    cli.close()
    srv.stop()

    ratio = coalesced_qps / seq_qps
    print(json.dumps({
        "metric": "serving_coalesced_throughput",
        "value": round(coalesced_qps, 2),
        "unit": "requests/sec",
        "vs_baseline": round(ratio, 3),
        "sequential_batch1_qps": round(seq_qps, 2),
        "hidden_width": SERVE_HIDDEN,
        "max_batch": SERVE_MAX_BATCH,
        "max_delay_ms": SERVE_MAX_DELAY_MS,
        "closed_loop_window": SERVE_WINDOW,
        "mean_occupancy": occupancy if occupancy is None
        else round(occupancy, 4),
        "paced_fraction": SERVE_PACED_FRACTION,
        "latency": best,
        "latency_rounds": rounds,
        "admission": {
            "p50_on_ms": round(min(on_p50), 3),
            "p50_off_ms": round(min(off_p50), 3),
            "overhead_pct": round(admission_overhead_pct, 2),
            "rounds": len(on_p50),
            "overhead_ceiling_pct": SERVE_ADMISSION_PCT,
        },
        "generation": stats["generation"],
        "bucket_hits": stats["batcher"]["bucket_hits"],
        "compiles_after_warmup": compiles_warm,
        "recompiles_mixed_stream": recompiles,
        "jit_cache_size": jit_cache,
        "shed": stats["rejected"],
        "timed_out": stats["timed_out"],
        "throughput_floor": SERVE_THROUGHPUT_FLOOR,
    }))
    # gates AFTER the JSON line (the record survives a trip)
    failures = []
    if ratio < SERVE_THROUGHPUT_FLOOR:
        failures.append(
            f"coalesced/sequential ratio {ratio:.2f} < "
            f"{SERVE_THROUGHPUT_FLOOR}x")
    if best["p99_ms"] > best["p99_bound_ms"]:
        failures.append(f"p99 {best['p99_ms']} ms > bound "
                        f"{best['p99_bound_ms']} ms "
                        f"(= {SERVE_P99_MULT} x ({SERVE_MAX_DELAY_MS} "
                        f"+ {best['batch_ms']}))")
    if recompiles:
        failures.append(f"{recompiles} recompiles during the mixed-size "
                        "stream (bucket ladder leak)")
    if admission_overhead_pct > SERVE_ADMISSION_PCT:
        failures.append(
            f"admission/deadline path adds "
            f"{admission_overhead_pct:.2f}% p50 at the "
            f"{SERVE_PACED_FRACTION}x operating point "
            f"(ceiling {SERVE_ADMISSION_PCT}%)")
    if failures:
        raise SystemExit("serving gates failed: " + "; ".join(failures))


#: --fleet protocol knobs (ISSUE 12).  Three gates over a real
#: 3-replica fleet behind the ReplicaBalancer, all RELATIVE to
#: same-process fault-free measurements (TPU-independent): (1) a seeded
#: kill-and-restart chaos run loses zero acknowledged requests (ledger
#: accepted == replied + refused) with goodput within band of
#: fault-free, (2) a canary rollover triggered MID-chaos completes with
#: every reply's generation stamp consistent with the wave, (3) a
#: forced parity-regression canary auto-rolls-back with the fleet still
#: serving the old generation bit-exactly.  The model is a thin MNIST
#: MLP — the fleet gates measure COORDINATION (failover, hedging,
#: rollover), not batch compute, so restart warmups must stay cheap on
#: this 1-core host.
FLEET_REPLICAS = 3
FLEET_HIDDEN = 256
FLEET_MAX_BATCH = 8
FLEET_RATE_QPS = 25.0       # open-loop offered load, single-row
FLEET_FAULTFREE_S = 8.0     # fault-free goodput window
FLEET_CHAOS_S = 24.0        # seeded kill/restart + rollover window
FLEET_SETTLE_S = 6.0        # post-chaos drain/heal window
FLEET_SWAP_AT_S = 5.0       # rollover trigger inside the chaos window
FLEET_GOODPUT_BAND = 0.45   # chaos goodput >= band x fault-free (2 of
#                             3 replicas die once each mid-window on a
#                             1-core host whose restarts recompile)
FLEET_SEED = 1207


def _build_fleet_workflow():
    """A thin MNIST MLP, seeded so every call builds BIT-IDENTICAL
    params — three replicas built this way answer bit-exactly alike,
    which is what the parity probes and per-generation oracles ride."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root

    prng.reset(1013)
    root.mnist.loader.n_train = 256
    root.mnist.loader.n_valid = 64
    root.mnist.loader.minibatch_size = 64
    root.mnist.layers = [FLEET_HIDDEN, 10]

    from znicz_tpu.samples import mnist

    try:
        wf = mnist.MnistWorkflow()
    finally:
        root.mnist.layers = [100, 10]
    wf.initialize(device=None)
    return wf


def fleet_main() -> None:
    """``--fleet``: the replica-balancer gates (ISSUE 12), one JSON
    line; gates AFTER the line so a trip never destroys the record."""
    import shutil
    import tempfile
    import time as _time

    from znicz_tpu.parallel.chaos import (FaultSchedule, ReplicaHarness,
                                          SubtreePreempter)
    from znicz_tpu.serving import InferenceClient, ReplicaBalancer

    sys.setswitchinterval(1e-3)

    tmp = tempfile.mkdtemp(prefix="znicz_fleet_")
    wf0 = _build_fleet_workflow()
    wf0.snapshotter.directory = tmp
    path_a = wf0.snapshotter.save("fleet_a")
    path_b = os.path.join(tmp, "fleet_b" + path_a[path_a.index("."):])
    shutil.copy(path_a, path_b)     # SAME params, distinct path: the
    # healthy rollover (parity must hold bit-exactly across it)
    for f in wf0.forwards:          # the broken "upgrade": perturbed
        for k, a in f.params().items():
            a.mem = np.asarray(a.map_read()) * np.float32(1.25) \
                + np.float32(0.01)
    path_bad = wf0.snapshotter.save("fleet_bad")

    # canary_p99_mult is WIDE here on purpose: mid-chaos, both old
    # replicas can be down at once, so the freshly-warmed canary
    # absorbs a parked-request burst whose queueing p99 is legitimate
    # load, not a regression — the healthy-wave gate is coordination +
    # PARITY; the p99-regression verdict itself is pinned under
    # controlled timing by the tier-1 scripted-canary test
    balancer = ReplicaBalancer(
        replica_ttl_s=1.2, failover_timeout_s=1.0, failover_tries=4,
        hedge_floor_s=0.4, canary_requests=20, parity_every=3,
        canary_timeout_s=30.0, canary_p99_mult=100.0,
        min_replicas=2).start()

    from znicz_tpu.serving import InferenceServer

    wfs = [_build_fleet_workflow() for _ in range(FLEET_REPLICAS)]
    binds = ["tcp://127.0.0.1:*"] * FLEET_REPLICAS

    def make_factory(i):
        def make():
            return InferenceServer(
                wfs[i], bind=binds[i], snapshot=path_a,
                max_batch=FLEET_MAX_BATCH, max_delay_ms=2.0,
                queue_bound=64, announce=balancer.endpoint,
                replica_id=f"r{i}")
        return make

    harnesses = [ReplicaHarness(make_factory(i))
                 for i in range(FLEET_REPLICAS)]
    for i, h in enumerate(harnesses):
        h.start()
        binds[i] = h.server.endpoint    # restarts rebind the same port
    t0 = _time.perf_counter()
    while balancer.ready_count() < FLEET_REPLICAS:
        if _time.perf_counter() - t0 > 60:
            raise SystemExit("fleet never became ready")
        _time.sleep(0.05)

    cli = InferenceClient(balancer.endpoint, timeout=25.0,
                          resend_after_s=60.0, breaker_failures=0)
    rng = np.random.default_rng(FLEET_SEED)
    x1 = rng.normal(0, 1, (1, 28 * 28)).astype(np.float32)

    infer_rids = set()
    answers: dict = {}              # rid -> (t_wall, ok, gen)
    gen_events: list = []           # (t_wall, gen) of ok replies

    def pump(wait=0.002):
        for rep in cli.collect(wait):
            rid = rep.get("req_id")
            if rid not in infer_rids:
                continue
            if rid in answers:
                raise SystemExit(f"req {rid} answered twice — "
                                 f"exactly-once broken")
            ok = bool(rep.get("ok"))
            answers[rid] = (_time.perf_counter(), ok, rep.get("gen"))
            if ok:
                gen_events.append((_time.perf_counter(), rep["gen"]))

    def drive(duration_s, on_tick=None):
        """Open-loop single-row arrivals at FLEET_RATE_QPS; returns
        (ok replies landed in-window, elapsed)."""
        n0_ok = sum(1 for _, ok, _ in answers.values() if ok)
        t0 = _time.perf_counter()
        i = 0
        while _time.perf_counter() - t0 < duration_s:
            now = _time.perf_counter() - t0
            if now >= i / FLEET_RATE_QPS and cli.in_flight < 256:
                infer_rids.add(cli.submit(x1))
                i += 1
            if on_tick is not None:
                on_tick(now)
            pump()
        elapsed = _time.perf_counter() - t0
        return (sum(1 for _, ok, _ in answers.values() if ok) - n0_ok,
                elapsed)

    def drain(budget_s=20.0):
        t0 = _time.perf_counter()
        while cli.in_flight and _time.perf_counter() - t0 < budget_s:
            pump(0.02)

    # ---- phase 1: fault-free goodput ------------------------------------
    ok_ff, el_ff = drive(FLEET_FAULTFREE_S)
    drain()
    goodput_ff = ok_ff / el_ff
    ledger_ff = balancer.ledger()

    # ---- phase 2: seeded kill/restart chaos + MID-chaos rollover --------
    # r1/r2 each die once on their own seeded timetable while the wave
    # (canary r0) runs; r0 is preempted LATE — after the wave should
    # have promoted — so the heal path (restart -> boot snapshot ->
    # re-swap onto the fleet path) is exercised too
    # r1 and r2 die in SERIALIZED seeded windows (a rolling
    # preemption): overlapping both kills against the canary warm
    # would measure a one-survivor fleet, which the goodput band — not
    # the rollover gate — is the honest judge of
    preempters = [
        SubtreePreempter(FaultSchedule(FLEET_SEED + 1),
                         [("r1", harnesses[1].kill,
                           harnesses[1].restart)],
                         kill_s=(2.0, 5.0), down_s=(2.0, 3.0)),
        SubtreePreempter(FaultSchedule(FLEET_SEED + 2),
                         [("r2", harnesses[2].kill,
                           harnesses[2].restart)],
                         kill_s=(9.0, 12.0), down_s=(2.0, 3.0)),
        SubtreePreempter(FaultSchedule(FLEET_SEED + 3),
                         [("r0", harnesses[0].kill,
                           harnesses[0].restart)],
                         kill_s=(16.0, 19.0), down_s=(2.0, 3.0)),
    ]
    swap_state = {"sent": False, "t_sent": None, "rid": None}

    def maybe_swap(now):
        if not swap_state["sent"] and now >= FLEET_SWAP_AT_S:
            swap_state["sent"] = True
            swap_state["t_sent"] = _time.perf_counter()
            swap_state["rid"] = cli._send({"cmd": "swap",
                                          "path": path_b})

    for p in preempters:
        p.start()
    ok_chaos, el_chaos = drive(FLEET_CHAOS_S, on_tick=maybe_swap)
    for p in preempters:
        p.join(timeout=60)
    # settle: drain the tail, let restarted replicas re-announce and
    # heal onto the promoted path
    t_settle0 = _time.perf_counter()
    drive(FLEET_SETTLE_S)
    drain()
    goodput_chaos = ok_chaos / el_chaos
    ledger_chaos = balancer.ledger()
    history = list(balancer.rollover_history)
    promoted = [h for h in history if h["result"] == "promoted"]
    gens_seen = sorted({g for _, g in gen_events})
    pre_swap_gen2 = [1 for t, g in gen_events
                     if swap_state["t_sent"] is not None
                     and t < swap_state["t_sent"] and g != 1]
    late_old_gen = [1 for t, g in gen_events
                    if t > t_settle0 + FLEET_SETTLE_S * 0.7 and g != 2]
    unanswered = [rid for rid in infer_rids if rid not in answers]
    fleet_stats = balancer.stats()

    # ---- phase 3: forced parity regression must auto-roll-back ----------
    pre_y = cli.result(cli.submit(x1))["y"]
    cli._send({"cmd": "swap", "path": path_bad})
    t0 = _time.perf_counter()
    while not balancer.rollbacks and _time.perf_counter() - t0 < 40:
        r = cli.submit(x1)
        infer_rids.add(r)
        pump(0.01)
    drain()
    regression = balancer.rollover_history[-1] if \
        balancer.rollover_history else {}
    post_y = cli.result(cli.submit(x1))["y"]
    post_gen = cli.result(cli.submit(x1))["gen"]
    bitexact_after_rollback = bool(
        np.array_equal(pre_y, post_y))
    ledger_final = balancer.ledger()

    record = {
        "metric": "fleet_chaos_goodput",
        "value": round(goodput_chaos, 2),
        "unit": "ok_replies/sec",
        "vs_faultfree": round(goodput_chaos / max(goodput_ff, 1e-9), 3),
        "goodput_faultfree": round(goodput_ff, 2),
        "goodput_band": FLEET_GOODPUT_BAND,
        "replicas": FLEET_REPLICAS,
        "rate_qps": FLEET_RATE_QPS,
        "seed": FLEET_SEED,
        "preemptions": sum(p.preemptions for p in preempters),
        "ledger_faultfree": ledger_ff,
        "ledger_chaos": ledger_chaos,
        "ledger_final": ledger_final,
        "unanswered": len(unanswered),
        "gens_seen": gens_seen,
        "pre_swap_gen2_replies": len(pre_swap_gen2),
        "late_old_gen_replies": len(late_old_gen),
        "rollover_history": history,
        "regression": regression,
        "bitexact_after_rollback": bitexact_after_rollback,
        "post_rollback_gen": post_gen,
        "failovers": balancer.failovers,
        "hedges": balancer.hedges,
        "hedge_wins": balancer.hedge_wins,
        "hedge_delay_ms": fleet_stats["hedge_delay_ms"],
        "dup_replies_dropped": balancer.dup_replies_dropped,
        "heals": balancer.heals,
        "replicas_lost": balancer.replicas_lost,
        "parity_checks": balancer.parity_checks,
        "parity_mismatches": balancer.parity_mismatches,
    }
    print(json.dumps(record))
    cli.close()
    balancer.stop()
    for h in harnesses:
        h.kill()
    # gates AFTER the JSON line (the record survives a trip)
    failures = []
    if not ledger_final["balanced"] or ledger_final["in_flight"]:
        failures.append(f"ledger leaked: {ledger_final}")
    if unanswered:
        failures.append(f"{len(unanswered)} acknowledged requests "
                        f"never answered (no reply, no refusal)")
    if goodput_chaos < FLEET_GOODPUT_BAND * goodput_ff:
        failures.append(
            f"chaos goodput {goodput_chaos:.1f}/s < "
            f"{FLEET_GOODPUT_BAND} x fault-free {goodput_ff:.1f}/s")
    if len(promoted) != 1:
        failures.append(f"expected exactly one promoted rollover "
                        f"mid-chaos, saw {history}")
    if gens_seen and (min(gens_seen) < 1 or max(gens_seen) > 2):
        failures.append(f"generation stamps outside the wave: "
                        f"{gens_seen}")
    if pre_swap_gen2:
        failures.append(f"{len(pre_swap_gen2)} replies stamped the NEW "
                        f"generation before the swap was even sent")
    if late_old_gen:
        failures.append(f"{len(late_old_gen)} replies still stamped "
                        f"the old generation after promote + heal "
                        f"settle")
    if regression.get("result") != "rolled_back":
        failures.append(f"forced parity regression did not auto-roll-"
                        f"back: {regression}")
    if not bitexact_after_rollback:
        failures.append("post-rollback fleet output differs from the "
                        "pre-swap generation (bit-exactness broken)")
    if balancer.parity_mismatches < 1:
        failures.append("the perturbed snapshot produced no parity "
                        "mismatch — the probe path cannot be live")
    shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        raise SystemExit("fleet gates failed: " + "; ".join(failures))


#: --shard protocol knobs (ISSUE 13): the pod-scale sharded-serving
#: gates, run on 8 VIRTUAL CPU devices (znicz_tpu/virtdev.py — the same
#: provisioning conftest/the MULTICHIP dryruns use), so they hold on
#: this TPU-less container and verify STRUCTURE: exact per-device shard
#: shapes, jit-cache hygiene, parity.  Throughput across layouts is
#: recorded but NOT gated — 8 virtual devices time-slice one throttled
#: core, so layout timing here is scheduling noise; the real-TPU
#: protocol lives in BASELINE.md.  The model is the 2048-hidden MNIST
#: MLP (the --serve model): wide enough that the ``model`` axis engages
#: (FusedTrainer.tp_threshold = 1024) and that gemm reduction tiling is
#: genuinely layout-dependent — which is WHY cross-layout parity is a
#: tight numerical band, not 0 ULP: XLA's reduction order changes with
#: the per-device operand shape, the same reason PR 4 pinned the 0-ULP
#: contract per bucket executable.  WITHIN a fixed mesh the 0-ULP
#: batch-independence contract is gated bit-exactly.
SHARD_DEVICES = 8
SHARD_MAX_BATCH = 32
SHARD_HIDDEN = SERVE_HIDDEN
#: cross-layout parity band: max |y_layout - y_single| over a rung,
#: relative to max |y_single| (measured here: ~5e-7..1.1e-6 — f32
#: reduction-order noise over the K=784/2048 contractions; the band
#: leaves ~10x headroom while still failing any real math divergence,
#: which would show up orders of magnitude larger)
SHARD_PARITY_REL = 1e-5
SHARD_LAYOUTS = (("d4", (4, 1)), ("d2m2", (2, 2)))
SHARD_MIXED_SIZES = (1, 2, 3, 5, 8, 13, 21, 32, 7, 2, 30, 16, 4)
SHARD_WINDOW_S = 1.0        # per-layout closed-loop timing window


def shard_main() -> None:
    """``--shard``: the sharded-serving gates (ISSUE 13), one JSON
    line.  Against the SAME workflow, a single-device reference runner
    and one mesh-native runner per layout in ``SHARD_LAYOUTS``:

      - **shard shapes**: for every ladder rung, the staged batch and
        the computed result both hold EXACTLY rows/dp rows on each of
        the dp data-axis devices (``addressable_shards``) — the "no
        gather through device 0" placement proof;
      - **jit hygiene**: warmup compiles exactly one executable per
        rung; a mixed-size request stream (sizes 1..max_batch, padded
        by the dp-snapped ladder) causes ZERO recompiles, by the trace
        counter AND jax's own pjit cache size;
      - **parity**: per rung, the sharded result matches the
        single-device reference within ``SHARD_PARITY_REL`` (see the
        knob comment for why cross-LAYOUT is a band), and the 0-ULP
        batch-independence contract (offset/neighbor/pad independence)
        holds bit-exactly WITHIN each mesh;
      - **mesh 1x1**: a runner built under the default mesh config IS
        the single-device path — results byte-identical to the
        reference runner, rung by rung;
      - **layouts**: {data:4} vs {data:2,model:2} rows/s recorded (not
        gated on this host — see the knob comment).

    Gates are enforced AFTER the JSON line so a tripped gate never
    destroys the measurement record it complains about."""
    import time as _time

    from znicz_tpu.virtdev import provision_cpu_devices

    # BEFORE the first backend init (conftest discipline): this gate
    # verifies sharding STRUCTURE, which needs >= 8 devices regardless
    # of what hardware the host has
    provision_cpu_devices(SHARD_DEVICES)

    from znicz_tpu.parallel.mesh import make_mesh
    from znicz_tpu.serving import BucketLadder, ModelRunner

    wf = _build_serve_workflow()
    sample_shape = tuple(int(d) for d in wf.forwards[0].input.shape[1:])
    rng = np.random.default_rng(1013)

    def pad(x, b):
        out = np.zeros((b,) + x.shape[1:], np.float32)
        out[:len(x)] = x
        return out

    # single-device reference: per-rung probe outputs
    ref = ModelRunner(wf)
    ref_ladder = BucketLadder(SHARD_MAX_BATCH)
    ref.warmup(ref_ladder)
    probes = {r: rng.normal(0, 1, (r,) + sample_shape).astype(np.float32)
              for r in BucketLadder(SHARD_MAX_BATCH, dp=max(
                  dp for _, (dp, _mp) in SHARD_LAYOUTS))}
    ref_y = {r: ref.infer(pad(probes[r], ref_ladder.bucket_for(r)))[:r]
             for r in probes}

    failures = []
    layouts = {}
    for tag, (dp, mp) in SHARD_LAYOUTS:
        runner = ModelRunner(
            wf, mesh=make_mesh((dp, mp), ("data", "model")))
        ladder = BucketLadder(SHARD_MAX_BATCH, dp=dp)
        if any(r % dp for r in ladder.rungs):
            failures.append(f"{tag}: ladder {ladder.rungs} not snapped "
                            f"to dp={dp}")
        warm = runner.warmup(ladder)
        rec = {"mesh": runner.mesh_shape, "devices": runner.device_count,
               "ladder": list(ladder.rungs), "compiles_warm": warm,
               "parity_rel": 0.0}
        # shard shapes + parity, rung by rung
        for rung in ladder:
            staged = runner.stage(pad(probes[rung]
                                      if rung in probes else
                                      rng.normal(0, 1, (rung,)
                                                 + sample_shape
                                                 ).astype(np.float32),
                                      rung))
            x_shards = [s.data.shape for s in staged.addressable_shards]
            y_dev, _gen = runner.infer_staged(staged)
            y_shards = [s.data.shape for s in y_dev.addressable_shards]
            want = rung // dp
            if (len(x_shards) != runner.device_count
                    or any(s[0] != want for s in x_shards)):
                failures.append(f"{tag}: rung {rung} staged shards "
                                f"{x_shards}, want {want} rows on each "
                                f"of {runner.device_count} devices")
            if any(s[0] != want for s in y_shards):
                failures.append(f"{tag}: rung {rung} result shards "
                                f"{y_shards}, want {want} rows each")
            if rung in probes:
                y = np.asarray(y_dev)[:rung]
                rel = float(np.max(np.abs(y - ref_y[rung]))
                            / max(np.max(np.abs(ref_y[rung])), 1e-30))
                rec["parity_rel"] = max(rec["parity_rel"], rel)
                if rel > SHARD_PARITY_REL:
                    failures.append(
                        f"{tag}: rung {rung} sharded-vs-single-device "
                        f"parity {rel:.2e} > {SHARD_PARITY_REL}")
        # 0-ULP batch-independence WITHIN this mesh: coalesced vs
        # alone-in-the-rung, plus garbage pad rows
        rung = ladder.rungs[min(1, len(ladder.rungs) - 1)]
        parts = [probes[rung][:rung // 2], probes[rung][rung // 2:]]
        alone = [runner.infer(pad(p, rung))[:len(p)] for p in parts]
        together = runner.infer(np.concatenate(parts))
        garbage = pad(parts[0], rung)
        garbage[len(parts[0]):] = 1e9
        if not (np.array_equal(together[:len(parts[0])], alone[0])
                and np.array_equal(together[len(parts[0]):], alone[1])
                and np.array_equal(
                    runner.infer(garbage)[:len(parts[0])], alone[0])):
            failures.append(f"{tag}: 0-ULP batch-independence broke "
                            f"on the sharded path (rung {rung})")
        # mixed-size stream: zero recompiles after warmup
        c0, j0 = runner.compiles, runner.jit_cache_size()
        for n in SHARD_MIXED_SIZES:
            runner.infer(pad(probes.get(
                n, rng.normal(0, 1, (n,) + sample_shape
                              ).astype(np.float32))[:n],
                ladder.bucket_for(n)))
        rec["recompiles_mixed_stream"] = runner.compiles - c0
        rec["jit_cache_size"] = runner.jit_cache_size()
        if runner.compiles != c0:
            failures.append(f"{tag}: {runner.compiles - c0} recompiles "
                            f"during the mixed-size stream")
        if j0 is not None and runner.jit_cache_size() != j0:
            failures.append(f"{tag}: jax jit cache grew "
                            f"{j0} -> {runner.jit_cache_size()} during "
                            f"the mixed-size stream")
        # layout timing (recorded, not gated on this host)
        xb = probes[SHARD_MAX_BATCH]
        rows = 0
        t0 = _time.perf_counter()
        while _time.perf_counter() - t0 < SHARD_WINDOW_S:
            runner.infer(xb)
            rows += SHARD_MAX_BATCH
        rec["rows_per_s"] = round(rows / (_time.perf_counter() - t0), 1)
        rec["stage_copies"] = runner.stage_copies
        layouts[tag] = rec

    # mesh 1x1 (default config) must BE the single-device path
    one = ModelRunner(wf)       # mesh_from_config() -> None by default
    one.warmup(ref_ladder)
    one_exact = all(
        np.array_equal(one.infer(pad(probes[r],
                                     ref_ladder.bucket_for(r)))[:r],
                       ref_y[r]) for r in probes)
    if one.mesh is not None:
        failures.append("default mesh config did not resolve to the "
                        "single-device path")
    if not one_exact:
        failures.append("mesh 1x1 results differ from the single-device "
                        "reference (must be byte-identical)")

    print(json.dumps({
        "metric": "serving_sharded_structure",
        "value": max(rec["parity_rel"] for rec in layouts.values()),
        "unit": "max_rel_parity_vs_single_device",
        "devices_provisioned": SHARD_DEVICES,
        "hidden_width": SHARD_HIDDEN,
        "max_batch": SHARD_MAX_BATCH,
        "parity_band": SHARD_PARITY_REL,
        "mesh_1x1_byte_identical": bool(one_exact),
        "layouts": layouts,
        "single_device_rows_per_s": None,   # see layouts: CPU timing
        #                                     noise — BASELINE.md r18
        #                                     carries the TPU protocol
    }))
    # gates AFTER the JSON line (the record survives a trip)
    if failures:
        raise SystemExit("shard gates failed: " + "; ".join(failures))


#: --shard-train protocol knobs (ISSUE 18): the pod-sliced TRAINING
#: gates, on the same 8 virtual CPU devices as --shard and with the
#: same structure-not-throughput discipline.  One seeded single-slave
#: MNIST fleet per scenario — the oracle (train_shard off), mesh 1x1
#: under train_shard (must BE the single-device path, bit-exact), and
#: the {data:4, model:2} pod slice — so the wire protocol, the job
#: stream, and the Decision are identical across scenarios and every
#: difference is attributable to the slice.  The model is the wide
#: MNIST MLP (hidden >= tp_threshold) so the model axis engages the
#: column-sharded layout; n_train/minibatch give 5 TRAIN minibatches
#: per epoch, and segment_steps=4 pins the steady-state scan length so
#: the post-run replay exercises exactly the executables the fleet
#: compiled (k=4 segment + k=1 tail).  bytes-into-master is gated at
#: <= 1% drift vs the oracle: the intra-slice psum tier is FREE on the
#: wire — a sharded slave must not change what crosses the host
#: boundary.  Convergence band reuses the --agg discipline (seeded
#: async replicas; both runs must land converged, within a band of
#: each other — the {4,2} run differs from the oracle only by XLA
#: reduction-order noise amplified through training).
SHARD_TRAIN_HIDDEN = 2048
SHARD_TRAIN_EPOCHS = 3
SHARD_TRAIN_N_TRAIN = 300
SHARD_TRAIN_SEGMENT = 4
SHARD_TRAIN_BASE_PORT = 18900
SHARD_TRAIN_BYTES_DRIFT = 0.01


def _shard_train_workflow(tag: str):
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = SHARD_TRAIN_N_TRAIN
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = SHARD_TRAIN_EPOCHS
    root.common.dirs.snapshots = f"/tmp/bench_shard_train/{tag}"
    root.mnist.layers = [SHARD_TRAIN_HIDDEN, 10]
    try:
        wf = mnist.MnistWorkflow()
    finally:
        root.mnist.layers = [100, 10]
    wf.initialize(device=None)
    return wf


def _shard_train_fleet(tag: str, port: int, dp: int, mp: int,
                       shard: bool):
    """One seeded single-slave fleet under the given engine-mesh
    config; returns ``(server, master_wf, slave, err_pct)`` with the
    slave's trainer still live for post-run inspection."""
    import threading

    from znicz_tpu.client import FusedClient
    from znicz_tpu.core.config import root
    from znicz_tpu.server import Server

    root.common.engine.train_shard = bool(shard)
    root.common.engine.mesh.data = int(dp)
    root.common.engine.mesh.model = int(mp)
    try:
        ep = f"tcp://127.0.0.1:{port}"
        wf = _shard_train_workflow(f"{tag}_m")
        server = Server(wf, endpoint=ep, job_timeout=120.0,
                        segment_steps=SHARD_TRAIN_SEGMENT)
        slave = FusedClient(_shard_train_workflow(f"{tag}_s"),
                            endpoint=ep, slave_id=f"{tag}w0")
        errors: list = []

        def worker():
            try:
                slave.run()
            except BaseException as e:
                errors.append((slave.slave_id, repr(e)))
                raise

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        server.serve()
        t.join(timeout=180)
        if errors:
            raise SystemExit(f"{tag}: slave crashed: {errors}")
        if t.is_alive():
            raise SystemExit(f"{tag}: slave hung")
        dec = wf.decision
        if not bool(dec.complete):
            raise SystemExit(f"{tag}: training did not complete")
        return server, wf, slave, float(dec.epoch_metrics[1]["err_pct"])
    finally:
        # the engine tree is process-global: leave it at the defaults
        root.common.engine.train_shard = False
        root.common.engine.mesh.data = 1
        root.common.engine.mesh.model = 1


def _shard_train_master_params(wf):
    return {f.name: {k: np.asarray(a.map_read())
                     for k, a in f.params().items()}
            for f in wf.forwards if f.has_weights}


def shard_train_main() -> None:
    """``--shard-train``: the pod-sliced training gates (ISSUE 18),
    one JSON line.  Three seeded single-slave fleets over the SAME
    wire protocol and job stream:

      - **oracle**: train_shard off — the single-device FusedClient;
      - **mesh 1x1**: train_shard ON with a 1x1 mesh must resolve to
        the single-device path — master's converged params
        byte-identical to the oracle's, err_pct equal;
      - **pod slice {data:4, model:2}**: shard shapes on the wide fc
        layer (8 addressable shards, hidden/mp rows each — the
        column-sharded layout, replicated over the data axis), the
        slice shape visible on the master (register piggyback), the
        SAME executable count as the oracle (explicit shardings add
        zero recompiles), zero recompiles on a post-run replay of the
        steady-state job shapes (k=4 segment + k=1 tail, numpy idx +
        committed state — both warmed argument forms), bytes-into-
        master within ``SHARD_TRAIN_BYTES_DRIFT`` of the oracle (the
        ICI psum tier is free on the wire), and seeded convergence
        inside the ``--agg``-style band.

    Gates fire AFTER the JSON line so a trip never destroys the
    measurement record."""
    from znicz_tpu.virtdev import provision_cpu_devices

    # BEFORE the first backend init (conftest discipline)
    provision_cpu_devices(SHARD_DEVICES)

    failures = []

    # single-device oracle
    srv_o, wf_o, sl_o, err_o = _shard_train_fleet(
        "sto", SHARD_TRAIN_BASE_PORT, 1, 1, shard=False)
    bytes_o = int(srv_o.bytes_in)
    comp_o = int(sl_o._trainer._m_compiles.value)
    if sl_o._trainer.mesh is not None:
        failures.append("oracle slave grew a mesh with train_shard off")

    # mesh 1x1 under train_shard: IS the single-device path, bit-exact
    srv_1, wf_1, sl_1, err_1 = _shard_train_fleet(
        "st1", SHARD_TRAIN_BASE_PORT + 1, 1, 1, shard=True)
    if sl_1._trainer.mesh is not None:
        failures.append("train_shard with a 1x1 mesh did not resolve "
                        "to the single-device path")
    p_o = _shard_train_master_params(wf_o)
    p_1 = _shard_train_master_params(wf_1)
    one_exact = (err_1 == err_o) and all(
        np.array_equal(p_1[n][k], p_o[n][k])
        for n in p_o for k in p_o[n])
    if not one_exact:
        failures.append("mesh 1x1 converged params differ from the "
                        "single-device oracle (must be byte-identical)")

    # the pod slice: {data:4, model:2}
    srv_s, wf_s, sl_s, err_s = _shard_train_fleet(
        "sts", SHARD_TRAIN_BASE_PORT + 2, 4, 2, shard=True)
    t = sl_s._trainer
    bytes_s = int(srv_s.bytes_in)
    comp_s = int(t._m_compiles.value)
    if t.mesh_shape != {"data": 4, "model": 2}:
        failures.append(f"slave mesh {t.mesh_shape}, want "
                        f"{{'data': 4, 'model': 2}}")
    meshes_seen = list(srv_s.slave_meshes.values())
    if meshes_seen != [{"data": 4, "model": 2}]:
        failures.append(f"master saw slave meshes {meshes_seen} — the "
                        f"register piggyback is broken")
    # shard shapes: the wide fc layer is column-sharded over the model
    # axis (hidden/mp rows per shard) and replicated over data
    shard_rec = {}
    for f in sl_s.workflow.forwards:
        if not f.has_weights:
            continue
        for k, arr in f.params().items():
            if arr.shape[0] != SHARD_TRAIN_HIDDEN:
                continue
            shards = [s.data.shape for s in
                      arr.devmem.addressable_shards]
            shard_rec[f"{f.name}.{k}"] = shards
            want = SHARD_TRAIN_HIDDEN // 2
            if (len(shards) != SHARD_DEVICES
                    or any(s[0] != want for s in shards)):
                failures.append(
                    f"{f.name}.{k}: shards {shards}, want dim0={want} "
                    f"on each of {SHARD_DEVICES} devices")
    if not shard_rec:
        failures.append(f"no param with dim0={SHARD_TRAIN_HIDDEN} "
                        f"found — the model axis never engaged")
    # jit hygiene: explicit shardings add ZERO executables vs the
    # oracle, and a post-run replay of the steady-state job shapes
    # (k=4 segment, k=1 tail; fresh numpy idx + committed state, the
    # two warmed argument forms) recompiles nothing
    if comp_s != comp_o:
        failures.append(f"sharded slave compiled {comp_s} executables "
                        f"vs oracle {comp_o} — sharding must not "
                        f"change the executable count")
    c0, j0 = int(t._m_compiles.value), dict(t.jit_cache_sizes())
    rng = np.random.default_rng(7)
    for k in (SHARD_TRAIN_SEGMENT, 1, SHARD_TRAIN_SEGMENT):
        idx = rng.integers(0, SHARD_TRAIN_N_TRAIN, (k, 60))
        mbs = [{"indices": idx[i].tolist(), "size": 60}
               for i in range(k)]
        sl_s._run_minibatch({"kind": "segment", "minibatches": mbs},
                            train=True)
    replay_recompiles = int(t._m_compiles.value) - c0
    if replay_recompiles:
        failures.append(f"{replay_recompiles} recompiles on the "
                        f"steady-state replay after warmup")
    if dict(t.jit_cache_sizes()) != j0:
        failures.append(f"jax jit cache grew {j0} -> "
                        f"{t.jit_cache_sizes()} on the replay")
    # two-tier reduction: the intra-slice psum is free on the wire —
    # bytes into the master must not drift
    drift = abs(bytes_s - bytes_o) / max(bytes_o, 1)
    if drift > SHARD_TRAIN_BYTES_DRIFT:
        failures.append(f"bytes into master drifted {drift:.2%} "
                        f"(oracle {bytes_o}, sharded {bytes_s}; "
                        f"ceiling {SHARD_TRAIN_BYTES_DRIFT:.0%})")
    # seeded convergence: the --agg discipline
    if abs(err_s - err_o) > AGG_CONV_BAND:
        failures.append(f"sharded err {err_s:.1f}% outside the band "
                        f"(oracle {err_o:.1f}%, band {AGG_CONV_BAND})")
    for tag, err in (("oracle", err_o), ("sharded", err_s)):
        if err > AGG_ERR_CEIL:
            failures.append(f"{tag} err {err:.1f}% > ceiling "
                            f"{AGG_ERR_CEIL}% — did not converge")

    print(json.dumps({
        "metric": "train_sharded_structure",
        "value": round(abs(err_s - err_o), 3),
        "unit": "abs_err_pct_delta_vs_single_device_oracle",
        "devices_provisioned": SHARD_DEVICES,
        "hidden_width": SHARD_TRAIN_HIDDEN,
        "mesh": {"data": 4, "model": 2},
        "err_pct": {"oracle": err_o, "mesh_1x1": err_1,
                    "sharded": err_s},
        "mesh_1x1_byte_identical": bool(one_exact),
        "bytes_into_master": {"oracle": bytes_o, "sharded": bytes_s,
                              "drift": round(drift, 5),
                              "ceiling": SHARD_TRAIN_BYTES_DRIFT},
        "compiles": {"oracle": comp_o, "sharded": comp_s},
        "replay_recompiles": replay_recompiles,
        "jit_cache_sizes": dict(t.jit_cache_sizes()),
        "shard_shapes": {k: [list(map(int, s)) for s in v]
                         for k, v in shard_rec.items()},
        "conv_band": AGG_CONV_BAND,
    }))
    # gates AFTER the JSON line (the record survives a trip)
    if failures:
        raise SystemExit("shard-train gates failed: "
                         + "; ".join(failures))


#: --seq protocol knobs (ISSUE 15): the variable-length serving gates.
#: The model is the charlm transformer widened so per-token COMPUTE
#: dominates per-request overhead (the --serve lesson: a toy-thin model
#: measures only codec/python overhead, which no ladder can win back);
#: the request stream is skewed SHORT (mean ~12 tokens vs a 64-token
#: window), the regime where a single-max-len ladder burns most of its
#: FLOPs on padding.  Gates are RELATIVE and interleaved best-of, per
#: the standing cgroup-swing discipline.
SEQ_MAX_BATCH = 8
SEQ_MAX_LEN = 256
SEQ_RUNGS = (8, 16, 32, 64, 128, 256)   # 4x6 executables to warm
SEQ_MODEL = {"vocab": 64, "embed": 256, "heads": 4, "ffn": 1024}
SEQ_MIXED_LENGTHS = (3, 5, 8, 12, 4, 16, 7, 9, 24, 6, 10, 32, 8, 14,
                     5, 100, 11, 4, 20, 8)
SEQ_WINDOW_S = 2.5          # per-service closed-loop window per round
SEQ_ROUNDS = 5              # interleaved best-of rounds (early exit on
#                             clearing the floor with margin): the 2-D
#                             service runs ~4x more batches per second
#                             than the 1-D baseline, so a cgroup-share
#                             dip taxes it harder — both services need
#                             a quiet-phase window before the ratio is
#                             meaningful
SEQ_GOODPUT_FLOOR = 2.0     # 2-D ladder vs single-max-len goodput
SEQ_PARITY_PROBES = 12      # co-batched masked-parity submissions
SEQ_WINDOW_INFLIGHT = 2 * SEQ_MAX_BATCH


def _build_seq_workflow():
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root

    prng.reset(1013)
    root.charlm.loader.update({"n_train": 64, "n_valid": 16,
                               "seq_len": SEQ_MAX_LEN})
    root.charlm.model.update(dict(SEQ_MODEL))

    from znicz_tpu.samples.charlm import CharLMWorkflow

    wf = CharLMWorkflow()
    wf.initialize(device=None)
    return wf


def seq_main() -> None:
    """``--seq``: the variable-length serving gates (ISSUE 15), one JSON
    line.  Three phases against the SAME charlm model on this host:

      - goodput: the 2-D (batch x seq) ladder service vs the single-
        max-len ladder service (every request padded to the full
        window client-side — exactly what a fixed-shape service forces
        a mixed-length stream to do), driven closed-loop with the SAME
        skewed-short stream in INTERLEAVED windows, best-of per
        service.  Goodput counts REAL tokens answered per second.
        Gate: 2-D >= SEQ_GOODPUT_FLOOR x single-max-len;
      - zero recompiles: the 2-D service compiles exactly its
        rungs x seq-rungs product at warmup and NOTHING over the mixed
        stream (trace counter + jax's own jit cache);
      - masked 0-ULP parity: a fixed probe request co-batched with
        every round of varying same-seq-rung neighbors (the batch's
        rows rung pinned, so the executable is fixed) must come back
        BIT-IDENTICAL every time — each reply a pure function of the
        request's own rows and own unpadded length.

    Gates are enforced AFTER the JSON line so a tripped gate never
    destroys the measurement record."""
    import time as _time

    from znicz_tpu.serving import InferenceClient, InferenceServer
    from znicz_tpu.serving.batcher import BucketLadder

    sys.setswitchinterval(1e-3)

    wf = _build_seq_workflow()
    vocab = SEQ_MODEL["vocab"]
    rng = np.random.default_rng(1013)

    from znicz_tpu.core.config import root

    root.common.serving.seq.rungs = list(SEQ_RUNGS)
    srv2d = InferenceServer(wf, max_batch=SEQ_MAX_BATCH,
                            max_delay_ms=5.0,
                            queue_bound=8 * SEQ_MAX_BATCH).start()
    assert srv2d.batcher.ladder.seq_rungs == list(SEQ_RUNGS)
    warm_compiles = srv2d.runner.compiles
    n_buckets = len(srv2d.batcher.ladder.buckets())
    # the single-max-len baseline: a plain 1-D ladder on the same
    # model — every request must arrive at the full trained window
    srv1d = InferenceServer(wf, max_batch=SEQ_MAX_BATCH,
                            max_delay_ms=5.0,
                            queue_bound=8 * SEQ_MAX_BATCH,
                            ladder=BucketLadder(SEQ_MAX_BATCH)).start()
    cli2d = InferenceClient(srv2d.endpoint, timeout=120,
                            breaker_failures=0)
    cli1d = InferenceClient(srv1d.endpoint, timeout=120,
                            breaker_failures=0)

    def req_of(length):
        return rng.integers(1, vocab, size=(1, length)).astype(np.uint8)

    def pad_full(x):
        out = np.zeros((x.shape[0], SEQ_MAX_LEN), np.uint8)
        out[:, :x.shape[1]] = x
        return out

    def drive(cli, duration_s, full_len):
        """Closed loop over the mixed-length stream; returns (real
        tokens answered, elapsed).  ``full_len``: pad every request to
        the full window client-side (the 1-D service's contract)."""
        tokens = 0
        real_of = {}
        i = 0
        t0 = _time.perf_counter()
        while _time.perf_counter() - t0 < duration_s:
            while cli.in_flight < SEQ_WINDOW_INFLIGHT:
                length = SEQ_MIXED_LENGTHS[i % len(SEQ_MIXED_LENGTHS)]
                i += 1
                x = req_of(length)
                rid = cli.submit(pad_full(x) if full_len else x)
                real_of[rid] = length
            for rep in cli.collect(0.002):
                real = real_of.pop(rep["req_id"], 0)
                if rep.get("ok"):
                    tokens += real
        elapsed = _time.perf_counter() - t0
        while cli.in_flight:          # drain the tail, uncounted
            for rep in cli.collect(0.01):
                real_of.pop(rep["req_id"], None)
        return tokens, elapsed

    # warm both request paths
    for _ in range(4):
        cli2d.infer(req_of(12))
        cli1d.infer(pad_full(req_of(12)))

    goodput_2d = 0.0
    goodput_1d = 0.0
    for _ in range(SEQ_ROUNDS):
        tok, el = drive(cli1d, SEQ_WINDOW_S, full_len=True)
        goodput_1d = max(goodput_1d, tok / el)
        tok, el = drive(cli2d, SEQ_WINDOW_S, full_len=False)
        goodput_2d = max(goodput_2d, tok / el)
        if goodput_2d >= 1.15 * SEQ_GOODPUT_FLOOR * goodput_1d:
            break                     # floor cleared with margin

    # zero recompiles over the whole mixed stream
    recompiles = srv2d.runner.compiles - warm_compiles
    jit_cache = srv2d.runner.jit_cache_size()

    # masked 0-ULP parity: probe (4 rows, len 10 -> seq rung 16)
    # co-batched with a same-rung 4-row filler each round — the batch
    # must be the (8, 16) executable every round (the 0-ULP contract
    # is per executable; PR 4/12).  A scheduler stall > max_delay_ms
    # between the two submits can split them into (4, 16) batches —
    # such a round proves nothing either way, so it is detected via
    # the "8x8"->"8x16" bucket-hit counter and retried, bounded.
    probe = rng.integers(1, vocab, size=(4, 10)).astype(np.uint8)
    parity_replies = []
    split_rounds = 0
    j = 0
    attempts = 0
    while len(parity_replies) < SEQ_PARITY_PROBES \
            and attempts < 3 * SEQ_PARITY_PROBES:
        attempts += 1
        hits_before = srv2d.batcher.bucket_hits.get("8x16", 0)
        filler_len = 9 + (j % 8)              # rungs to 16, varies
        j += 1
        filler = rng.integers(1, vocab,
                              size=(4, filler_len)).astype(np.uint8)
        rid_p = cli2d.submit(probe)
        rid_f = cli2d.submit(filler)
        got = {}
        while len(got) < 2:
            for rep in cli2d.collect(0.05):
                got[rep["req_id"]] = rep
        assert got[rid_p].get("ok") and got[rid_f].get("ok"), got
        if srv2d.batcher.bucket_hits.get("8x16", 0) != hits_before + 1:
            split_rounds += 1                 # did not coalesce: retry
            continue
        parity_replies.append(got[rid_p]["y"])
    parity_exact = len(parity_replies) == SEQ_PARITY_PROBES and all(
        np.array_equal(parity_replies[0], y) for y in parity_replies[1:])

    pad_ratio = srv2d.batcher.pad_ratio()
    stats2d = srv2d.batcher.stats()
    for c in (cli2d, cli1d):
        c.close()
    for s in (srv2d, srv1d):
        s.stop()

    ratio = goodput_2d / max(goodput_1d, 1e-9)
    print(json.dumps({
        "metric": "seq_serving_goodput_ratio",
        "value": round(ratio, 3),
        "unit": "2d_ladder_vs_single_max_len_real_tokens_per_s",
        "goodput_2d_tok_s": round(goodput_2d, 1),
        "goodput_1d_tok_s": round(goodput_1d, 1),
        "goodput_floor": SEQ_GOODPUT_FLOOR,
        "max_batch": SEQ_MAX_BATCH,
        "max_len": SEQ_MAX_LEN,
        "seq_rungs": list(SEQ_RUNGS),
        "model": dict(SEQ_MODEL),
        "warm_compiles": warm_compiles,
        "buckets": n_buckets,
        "recompiles_mixed_stream": recompiles,
        "jit_cache_size": jit_cache,
        "parity_masked_bit_exact": bool(parity_exact),
        "parity_rounds": len(parity_replies),
        "parity_split_rounds_retried": split_rounds,
        "pad_ratio_by_bucket": pad_ratio,
        "padded_cells": stats2d["padded_cells"],
        "real_cells": stats2d["real_cells"],
    }))
    # gates AFTER the JSON line (the record survives a trip)
    failures = []
    if ratio < SEQ_GOODPUT_FLOOR:
        failures.append(f"mixed-length goodput ratio {ratio:.2f} below "
                        f"the {SEQ_GOODPUT_FLOOR}x floor")
    if warm_compiles != n_buckets:
        failures.append(f"warmup compiled {warm_compiles} executables, "
                        f"expected rungs x seq_rungs = {n_buckets}")
    if recompiles:
        failures.append(f"{recompiles} recompiles during the mixed "
                        f"stream (must be 0)")
    if jit_cache is not None and jit_cache != warm_compiles:
        failures.append(f"jax jit cache {jit_cache} != warmup "
                        f"compiles {warm_compiles}")
    if not parity_exact:
        failures.append("probe replies differ across co-batched "
                        "neighbor lengths (masked 0-ULP contract)")
    if failures:
        raise SystemExit("seq gates failed: " + "; ".join(failures))


#: --generate protocol knobs (ISSUE 16): the generation-serving gates.
#: Same model-sizing lesson as --seq (compute must dominate per-token
#: overhead or the bench measures python, not the KV cache); the
#: trained window is 64 so oracle prefixes stay inside the scoring
#: ladder.  Gates are RELATIVE and interleaved best-of, per the
#: standing cgroup-swing discipline.
GEN_MAX_BATCH = 8
GEN_TRAIN_LEN = 64
GEN_SEQ_RUNGS = (8, 16, 64)      # prompt ladder == scoring seq ladder
GEN_PAGE_SIZE = 64               # KV page grain: coarse for the no-reuse path
                                 # (one page covers the 64-token window; the
                                 # --prefix bench runs the fine 16-token grain
                                 # where sharing pays for the gather)
GEN_SLOTS = 32                   # concurrent generations resident
GEN_PROMPTS = (3, 5, 8, 12, 4, 14, 7, 9, 6, 10)      # mixed lengths
GEN_MAX_NEW = (24, 40, 32, 48, 28, 36, 40, 44, 48, 32)  # mixed budgets
GEN_INFLIGHT = 24                # concurrent generations offered
ORACLE_INFLIGHT = 4              # concurrent oracle token loops
GEN_WINDOW_S = 2.5               # per-path closed-loop window per round
GEN_ROUNDS = 4                   # interleaved best-of rounds
GEN_TPS_FLOOR = 10.0             # generation vs re-prefill oracle
GEN_PARITY_ROUNDS = 4            # co-batched bit-exactness rounds
GEN_PROBE_LEN = 6
GEN_PROBE_NEW = 40               # fill crosses page boundaries mid-run


def generate_main() -> None:
    """``--generate``: the generation-serving gates (ISSUE 16), one
    JSON line.  Three phases against ONE server (generation enabled on
    the charlm transformer of --seq sizing):

      - tokens/s: closed-loop ``generate`` traffic (mixed prompt
        lengths x mixed max_new budgets) vs the naive re-prefill
        oracle — a client loop that emits each token by scoring its
        sequence's WHOLE prefix through the same server's classic
        plane and sampling client-side, i.e. exactly what a
        scoring-only service forces generation to do.  Interleaved
        best-of windows; gate: generation >= GEN_TPS_FLOOR x oracle,
        with generation's p99 inter-token gap (the scheduler's
        per-sequence emission histogram) no worse than the oracle's
        client-stamped per-token p99;
      - per-decoded-token bit-exactness: a greedy probe generation
        co-batched with rounds of same-shape neighbors whose CONTENT
        (and sampled continuations) vary — the probe's per-token
        logits must come back BIT-IDENTICAL every round (executables
        pinned by same-shape neighbors; each row's decode reads only
        its own KV pages), and its token stream must match the solo
        run exactly (crossing page-table rungs mid-generation);
      - zero recompiles: warmup compiles == scoring buckets + the
        paged prefill/decode x (batch rung, page rung) family + the
        COW copy, and NOTHING recompiles over the whole mixed stream.

    Gates are enforced AFTER the JSON line so a tripped gate never
    destroys the measurement record."""
    import time as _time

    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.serving import InferenceClient, InferenceServer

    sys.setswitchinterval(1e-3)

    prng.reset(1013)
    root.charlm.loader.update({"n_train": 64, "n_valid": 16,
                               "seq_len": GEN_TRAIN_LEN})
    root.charlm.model.update(dict(SEQ_MODEL))

    from znicz_tpu.samples.charlm import CharLMWorkflow

    wf = CharLMWorkflow()
    wf.initialize(device=None)
    vocab = SEQ_MODEL["vocab"]
    rng = np.random.default_rng(1013)

    root.common.serving.seq.rungs = list(GEN_SEQ_RUNGS)
    root.common.serving.generate.update({
        "enabled": True, "page_size": GEN_PAGE_SIZE,
        "slots": GEN_SLOTS})
    srv = InferenceServer(wf, max_batch=GEN_MAX_BATCH, max_delay_ms=5.0,
                          queue_bound=8 * GEN_MAX_BATCH).start()
    warm_compiles = srv.runner.compiles
    n_buckets = len(srv.batcher.ladder.buckets())
    gen_execs = srv.gen_sched.gen.executables()
    cli = InferenceClient(srv.endpoint, timeout=120, breaker_failures=0)

    def prompt_of(length):
        return rng.integers(1, vocab, size=length).astype(np.uint8)

    # warm both request paths (compiles all counted in warm_compiles'
    # baseline? no — warmup() already compiled every executable; these
    # drive the warmed shapes only)
    cli.infer(prompt_of(12)[None])
    cli.generate(prompt_of(5), max_new_tokens=4)

    def drive_generate(duration_s):
        """Closed-loop generation window: keep GEN_INFLIGHT generations
        going; returns (tokens emitted by finals landing inside the
        window, elapsed).  Inter-token cadence comes from the
        scheduler's own per-sequence emission histogram, so the
        throughput path ships no per-token partials."""
        toks = 0
        i = 0
        t0 = _time.perf_counter()
        while _time.perf_counter() - t0 < duration_s:
            # hysteresis refill: submit in BURSTS so the scheduler's
            # prefill coalescing sees real batches, not singletons
            if cli.in_flight <= GEN_INFLIGHT - 4:
                while cli.in_flight < GEN_INFLIGHT:
                    plen = GEN_PROMPTS[i % len(GEN_PROMPTS)]
                    mnew = GEN_MAX_NEW[i % len(GEN_MAX_NEW)]
                    i += 1
                    cli.submit_generate(prompt_of(plen), mnew)
            for rep in cli.collect(0.002):
                if rep.get("ok"):
                    toks += len(rep["tokens"])
        elapsed = _time.perf_counter() - t0
        while cli.in_flight:            # drain the tail, uncounted
            cli.collect(0.01)
        return toks, elapsed

    def drive_oracle(duration_s):
        """The naive re-prefill oracle: ORACLE_INFLIGHT client-side
        token loops, each emitting its next token by scoring its whole
        prefix through the classic plane and argmaxing the last real
        position — O(prefix) recompute per emitted token."""
        toks = 0
        gaps = []
        i = 0

        def new_seq():
            nonlocal i
            plen = GEN_PROMPTS[i % len(GEN_PROMPTS)]
            mnew = GEN_MAX_NEW[i % len(GEN_MAX_NEW)]
            i += 1
            return {"prefix": list(prompt_of(plen)), "left": mnew,
                    "t_last": None}
        live = {}                       # rid -> seq state
        idle = [new_seq() for _ in range(ORACLE_INFLIGHT)]
        t0 = _time.perf_counter()
        while _time.perf_counter() - t0 < duration_s:
            while idle:
                s = idle.pop()
                x = np.asarray(s["prefix"], np.uint8)[None]
                live[cli.submit(x)] = s
            for rep in cli.collect(0.002):
                s = live.pop(rep["req_id"], None)
                if s is None or not rep.get("ok"):
                    continue
                row = rep["y"][0, len(s["prefix"]) - 1]
                s["prefix"].append(int(np.argmax(row)))
                s["left"] -= 1
                now = _time.perf_counter()
                if s["t_last"] is not None:
                    gaps.append(now - s["t_last"])
                s["t_last"] = now
                toks += 1
                idle.append(new_seq() if s["left"] <= 0 else s)
        elapsed = _time.perf_counter() - t0
        while cli.in_flight:            # drain the tail, uncounted
            for rep in cli.collect(0.01):
                live.pop(rep["req_id"], None)
        return toks, elapsed, gaps

    gen_tps = oracle_tps = 0.0
    oracle_gaps = []
    for _ in range(GEN_ROUNDS):
        tok, el, gaps = drive_oracle(GEN_WINDOW_S)
        oracle_tps = max(oracle_tps, tok / el)
        oracle_gaps.extend(gaps)
        tok, el = drive_generate(GEN_WINDOW_S)
        gen_tps = max(gen_tps, tok / el)
        if gen_tps >= 1.15 * GEN_TPS_FLOOR * oracle_tps:
            break                       # floor cleared with margin

    gen_p99_ms = srv.gen_sched.inter_token_quantiles().get(
        "inter_token_p99_ms")
    oracle_p99_ms = round(float(np.percentile(oracle_gaps, 99)) * 1e3,
                          3) if oracle_gaps else None

    # per-decoded-token bit-exactness: solo reference, then co-batched
    # rounds — neighbor SHAPES fixed (lengths 5/7/8, same max_new, so
    # every tick's decode/prefill executable is pinned across rounds),
    # neighbor CONTENT and sampled continuations vary per round
    probe = prompt_of(GEN_PROBE_LEN)
    solo = cli.generate(probe, GEN_PROBE_NEW, return_logits=True)
    probe_logits = []
    probe_tokens = [solo["tokens"]]
    split_rounds = 0
    attempts = 0
    while len(probe_logits) < GEN_PARITY_ROUNDS \
            and attempts < 3 * GEN_PARITY_ROUNDS:
        attempts += 1
        pb = srv.gen_sched.prefill_batches
        rid_p = cli.submit_generate(probe, GEN_PROBE_NEW,
                                    return_logits=True)
        rids_n = [cli.submit_generate(prompt_of(n_len), GEN_PROBE_NEW,
                                      temperature=0.9,
                                      seed=1000 * attempts + k)
                  for k, n_len in enumerate((5, 7, 8))]
        reps = {}
        while any(r not in reps for r in [rid_p] + rids_n):
            for rep in cli.collect(0.02):
                reps[rep["req_id"]] = rep
        assert reps[rid_p].get("ok"), reps[rid_p]
        if srv.gen_sched.prefill_batches != pb + 1:
            split_rounds += 1           # did not co-batch: proves
            continue                    # nothing either way — retry
        probe_logits.append(reps[rid_p]["logits"])
        probe_tokens.append(reps[rid_p]["tokens"])
    parity_bit_exact = len(probe_logits) == GEN_PARITY_ROUNDS and all(
        np.array_equal(probe_logits[0], lg) for lg in probe_logits[1:])
    tokens_pure = all(np.array_equal(probe_tokens[0], t)
                      for t in probe_tokens[1:])

    # zero recompiles over everything that just ran
    recompiles = srv.runner.compiles - warm_compiles
    jit_cache = srv.runner.jit_cache_size()
    gen_jit_cache = srv.gen_sched.gen.jit_cache_size()
    gstats = srv.gen_sched.stats()
    cli.close()
    srv.stop()

    ratio = gen_tps / max(oracle_tps, 1e-9)
    print(json.dumps({
        "metric": "generate_serving_tokens_per_s_ratio",
        "value": round(ratio, 3),
        "unit": "kv_decode_vs_reprefill_oracle_tokens_per_s",
        "generate_tok_s": round(gen_tps, 1),
        "oracle_tok_s": round(oracle_tps, 1),
        "tps_floor": GEN_TPS_FLOOR,
        "inter_token_p99_ms": gen_p99_ms,
        "oracle_token_p99_ms": oracle_p99_ms,
        "model": dict(SEQ_MODEL),
        "train_len": GEN_TRAIN_LEN,
        "page_size": gstats["page_size"],
        "num_pages": gstats["num_pages"],
        "prefill_chunk": gstats["prefill_chunk"],
        "prompt_rungs": list(GEN_SEQ_RUNGS),
        "slots": GEN_SLOTS,
        "warm_compiles": warm_compiles,
        "scoring_buckets": n_buckets,
        "generation_executables": gen_execs,
        "recompiles_mixed_stream": recompiles,
        "jit_cache_size": jit_cache,
        "gen_jit_cache_size": gen_jit_cache,
        "parity_logits_bit_exact": bool(parity_bit_exact),
        "parity_tokens_pure": bool(tokens_pure),
        "parity_rounds": len(probe_logits),
        "parity_split_rounds_retried": split_rounds,
        "cow_copies": gstats["cow_copies"],
        "prefix_hits": gstats["prefix_hits"],
        "pages_leaked": gstats["pages_leaked"],
        "prefill_batches": gstats["prefill_batches"],
        "decode_batches": gstats["decode_batches"],
        "generated_tokens": gstats["generated_tokens"],
    }))
    # gates AFTER the JSON line (the record survives a trip)
    failures = []
    if ratio < GEN_TPS_FLOOR:
        failures.append(f"generation tokens/s only {ratio:.2f}x the "
                        f"re-prefill oracle (floor {GEN_TPS_FLOOR}x)")
    if gen_p99_ms is not None and oracle_p99_ms is not None \
            and gen_p99_ms > oracle_p99_ms:
        failures.append(f"inter-token p99 {gen_p99_ms}ms worse than "
                        f"the oracle's per-token p99 {oracle_p99_ms}ms")
    if warm_compiles != n_buckets + gen_execs:
        failures.append(f"warmup compiled {warm_compiles}, expected "
                        f"scoring buckets {n_buckets} + generation "
                        f"executables {gen_execs}")
    if recompiles:
        failures.append(f"{recompiles} recompiles during the mixed "
                        f"stream (must be 0)")
    if gstats["pages_leaked"]:
        failures.append(f"{gstats['pages_leaked']} KV pages leaked "
                        f"(refcount invariant)")
    if jit_cache is not None and jit_cache != n_buckets:
        failures.append(f"scoring jit cache {jit_cache} != "
                        f"{n_buckets} buckets")
    if gen_jit_cache is not None and gen_jit_cache != gen_execs:
        failures.append(f"generation jit cache {gen_jit_cache} != "
                        f"{gen_execs} executables")
    if not parity_bit_exact:
        failures.append("probe logits differ across co-batched "
                        "neighbor-content rounds (bit-exactness "
                        "contract)")
    if not tokens_pure:
        failures.append("probe token stream depends on co-batched "
                        "neighbors (purity contract)")
    if failures:
        raise SystemExit("generate gates failed: " + "; ".join(failures))


#: --prefix protocol knobs (ISSUE 19): the paged-KV gates.  Sized to
#: the --seq/--generate transformer (window GEN_TRAIN_LEN=64, page 16,
#: chunk == page so prefix hits replay cold executables bit-exactly).
PFX_SHARED = 48                  # shared system-prompt tokens (3 pages)
PFX_STREAM = 10                  # shared-prefix requests per pass
PFX_TAILS = (4, 6, 8, 5, 7, 4, 8, 6, 5, 7)   # unique tail lengths
PFX_MAX_NEW = 4                  # greedy continuation per request
PFX_RATIO_CEIL = 0.5             # on/off prefilled-token ratio gate
PFX_STREAMERS = 4                # paced decoders in the latency phases
PFX_STREAM_NEW = 56              # tokens per decoder (fills to window)
PFX_TICK_MS = 40.0               # decode pacing (the band's metronome)
PFX_BARRAGE_LEN = 60             # long-prompt barrage (4 chunks each)
PFX_BARRAGE_INFLIGHT = 3         # barrage prompts resident
PFX_P99_BAND = 1.5               # barrage p99 <= band x this
PFX_BYTES_RATIO = 64             # logits-path bytes >= this x tokens-path


def prefix_main() -> None:
    """``--prefix``: the paged-KV gates (ISSUE 19), one JSON line.

    Four phases, two boots of the same charlm server:

      - prefill reduction: a seeded stream of PFX_STREAM prompts
        sharing a PFX_SHARED-token system prefix (unique short tails)
        runs against a prefix-cache-OFF boot (host sampling — also the
        logits-bytes baseline) and then a prefix-ON boot; the ON run
        must COMPUTE <= PFX_RATIO_CEIL x the prompt tokens the OFF run
        computed, with every decoded stream bit-exact between the two
        (chunk == page_size, so a hit replays the cold executables);
      - chunked-prefill latency: PFX_STREAMERS paced decoders
        (decode_tick_ms metronome) run once alone (the band) and once
        against a barrage of unique PFX_BARRAGE_LEN-token prompts; the
        decoders' client-stamped p99 inter-token gap under barrage
        must stay within PFX_P99_BAND x the band — a long prompt costs
        one bounded chunk per tick, never a whole-prompt stall;
      - on-device sampling bytes: the ON boot ships (b,) tokens per
        tick, the OFF boot (b, vocab) logits — fetched bytes per
        emitted token must differ by >= PFX_BYTES_RATIO (the vocab-64
        model's exact token/logits row ratio), greedy tokens already
        proven bit-identical by phase 1;
      - zero recompiles on the ON boot over everything above, both jit
        caches gated by strict equality.

    Gates are enforced AFTER the JSON line so a tripped gate never
    destroys the measurement record."""
    import time as _time

    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.serving import InferenceClient, InferenceServer

    sys.setswitchinterval(1e-3)

    prng.reset(1013)
    root.charlm.loader.update({"n_train": 64, "n_valid": 16,
                               "seq_len": GEN_TRAIN_LEN})
    root.charlm.model.update(dict(SEQ_MODEL))

    from znicz_tpu.samples.charlm import CharLMWorkflow

    wf = CharLMWorkflow()
    wf.initialize(device=None)
    vocab = SEQ_MODEL["vocab"]
    rng = np.random.default_rng(1013)
    shared = rng.integers(1, vocab, size=PFX_SHARED).astype(np.uint8)
    prompts = [np.concatenate(
                   [shared, rng.integers(1, vocab, size=t
                                         ).astype(np.uint8)])
               for t in PFX_TAILS]

    root.common.serving.seq.rungs = list(GEN_SEQ_RUNGS)

    def boot(prefix_on):
        root.common.serving.generate.update({
            "enabled": True, "page_size": GEN_PAGE_SIZE,
            "slots": 8, "prefix_cache": bool(prefix_on),
            "on_device_sampling": bool(prefix_on),
            "decode_tick_ms": PFX_TICK_MS if prefix_on else 0.0})
        srv = InferenceServer(wf, max_batch=GEN_MAX_BATCH,
                              max_delay_ms=5.0,
                              queue_bound=8 * GEN_MAX_BATCH).start()
        return srv, InferenceClient(srv.endpoint, timeout=120,
                                    breaker_failures=0)

    def shared_stream(srv, cli):
        """The shared-prefix pass: serial greedy generations; returns
        (token streams, prompt tokens computed, bytes fetched,
        tokens emitted)."""
        st0 = srv.gen_sched.stats()
        toks = [cli.generate(p, PFX_MAX_NEW)["tokens"] for p in prompts]
        st1 = srv.gen_sched.stats()
        return (toks,
                st1["prefill_tokens"] - st0["prefill_tokens"],
                st1["fetch_bytes"] - st0["fetch_bytes"],
                st1["generated_tokens"] - st0["generated_tokens"])

    # ---- OFF boot: the baseline side of phases 1 and 3 -----------------------
    srv, cli = boot(prefix_on=False)
    toks_off, prefill_off, bytes_off, emitted_off = shared_stream(srv, cli)
    cli.close()
    srv.stop()

    # ---- ON boot: everything else runs here ----------------------------------
    srv, cli = boot(prefix_on=True)
    warm_compiles = srv.runner.compiles
    n_buckets = len(srv.batcher.ladder.buckets())
    gen_execs = srv.gen_sched.gen.executables()
    toks_on, prefill_on, bytes_on, emitted_on = shared_stream(srv, cli)
    prefix_exact = all(np.array_equal(a, b)
                       for a, b in zip(toks_off, toks_on))
    prefill_ratio = prefill_on / max(prefill_off, 1)
    gstats_mid = srv.gen_sched.stats()

    def stream_phase(barrage):
        """PFX_STREAMERS streaming decoders, client-stamped; with
        ``barrage``, unique long prompts kept resident alongside.
        Returns the decoders' p99 inter-token gap in ms."""
        stamps = []
        streamer_rids = []
        for _ in range(PFX_STREAMERS):
            p = rng.integers(1, vocab, size=4).astype(np.uint8)
            s = []
            stamps.append(s)
            streamer_rids.append(cli.submit_generate(
                p, PFX_STREAM_NEW, stream=True,
                on_token=lambda tok, i, s=s:
                    s.append(_time.perf_counter())))
        pending = set(streamer_rids)
        barrage_live = set()
        barrage_done = 0
        while pending:
            if barrage:
                while len(barrage_live) < PFX_BARRAGE_INFLIGHT:
                    long_p = rng.integers(1, vocab, size=PFX_BARRAGE_LEN
                                          ).astype(np.uint8)
                    barrage_live.add(cli.submit_generate(long_p, 2))
            for rep in cli.collect(0.01):
                if rep.get("partial"):
                    continue
                rid = rep.get("req_id")
                pending.discard(rid)
                if rid in barrage_live:
                    barrage_live.discard(rid)
                    barrage_done += 1
        while cli.in_flight:            # drain the barrage tail
            cli.collect(0.02)
        gaps = [b - a for s in stamps for a, b in zip(s, s[1:])]
        return (round(float(np.percentile(gaps, 99)) * 1e3, 3),
                len(gaps), barrage_done)

    band_p99, band_gaps, _ = stream_phase(barrage=False)
    barrage_p99, barrage_gaps, barrage_n = stream_phase(barrage=True)

    recompiles = srv.runner.compiles - warm_compiles
    jit_cache = srv.runner.jit_cache_size()
    gen_jit_cache = srv.gen_sched.gen.jit_cache_size()
    gstats = srv.gen_sched.stats()
    cli.close()
    srv.stop()

    bytes_ratio = ((bytes_off / max(emitted_off, 1))
                   / max(bytes_on / max(emitted_on, 1), 1e-9))
    print(json.dumps({
        "metric": "prefix_cache_prefill_token_ratio",
        "value": round(prefill_ratio, 3),
        "unit": "prefix_on_vs_off_prompt_tokens_computed",
        "ratio_ceil": PFX_RATIO_CEIL,
        "prefill_tokens_off": int(prefill_off),
        "prefill_tokens_on": int(prefill_on),
        "prefix_outputs_bit_exact": bool(prefix_exact),
        "prefix_hits": gstats_mid["prefix_hits"],
        "prefix_tokens_avoided": gstats_mid["prefix_tokens_avoided"],
        "shared_prefix_tokens": PFX_SHARED,
        "model": dict(SEQ_MODEL),
        "page_size": gstats["page_size"],
        "num_pages": gstats["num_pages"],
        "prefill_chunk": gstats["prefill_chunk"],
        "decode_tick_ms": PFX_TICK_MS,
        "band_p99_ms": band_p99,
        "barrage_p99_ms": barrage_p99,
        "p99_band_factor": PFX_P99_BAND,
        "band_gaps": band_gaps,
        "barrage_gaps": barrage_gaps,
        "barrage_prompts_served": barrage_n,
        "fetch_bytes_per_token_off": round(bytes_off / max(emitted_off,
                                                           1), 1),
        "fetch_bytes_per_token_on": round(bytes_on / max(emitted_on,
                                                         1), 1),
        "bytes_ratio": round(bytes_ratio, 1),
        "bytes_ratio_floor": PFX_BYTES_RATIO,
        "warm_compiles": warm_compiles,
        "scoring_buckets": n_buckets,
        "generation_executables": gen_execs,
        "recompiles_mixed_stream": recompiles,
        "jit_cache_size": jit_cache,
        "gen_jit_cache_size": gen_jit_cache,
        "cow_copies": gstats["cow_copies"],
        "pages_leaked": gstats["pages_leaked"],
    }))
    # gates AFTER the JSON line (the record survives a trip)
    failures = []
    if prefill_ratio > PFX_RATIO_CEIL:
        failures.append(f"prefix-on computed {prefill_ratio:.2f}x the "
                        f"off run's prompt tokens (ceil "
                        f"{PFX_RATIO_CEIL}x)")
    if not prefix_exact:
        failures.append("decoded streams diverge between prefix-on "
                        "and prefix-off (bit-exact reuse contract)")
    if barrage_p99 > PFX_P99_BAND * band_p99:
        failures.append(f"barrage p99 {barrage_p99}ms outside "
                        f"{PFX_P99_BAND}x the {band_p99}ms band "
                        f"(chunked prefill must bound the stall)")
    if bytes_ratio < PFX_BYTES_RATIO:
        failures.append(f"logits path only {bytes_ratio:.1f}x the "
                        f"token path's bytes/token (floor "
                        f"{PFX_BYTES_RATIO}x)")
    if recompiles:
        failures.append(f"{recompiles} recompiles during the mixed "
                        f"stream (must be 0)")
    if gstats["pages_leaked"]:
        failures.append(f"{gstats['pages_leaked']} KV pages leaked "
                        f"(refcount invariant)")
    if jit_cache is not None and jit_cache != n_buckets:
        failures.append(f"scoring jit cache {jit_cache} != "
                        f"{n_buckets} buckets")
    if gen_jit_cache is not None and gen_jit_cache != gen_execs:
        failures.append(f"generation jit cache {gen_jit_cache} != "
                        f"{gen_execs} executables")
    if failures:
        raise SystemExit("prefix gates failed: " + "; ".join(failures))


#: --telemetry protocol knobs (ISSUE 5).  Same de-flake discipline as
#: --serve / the PR-4 snapshot guard: enabled/disabled windows are
#: INTERLEAVED (this container's cgroup CPU share swings minute to
#: minute — a load spike must hit both variants), the comparison is
#: best-of per variant, and rounds early-exit once the gate holds.
TELEMETRY_EPOCHS = 3        # epochs per timed window
TELEMETRY_MAX_ROUNDS = 6    # bounded interleaved best-of pairs
TELEMETRY_GATE_PCT = 2.0    # enabled may cost at most this much


#: --elastic protocol knobs (ISSUE 17): zero-cold-start elasticity.
#: Two phases.  (A) The AOT executable cache on the FULL transformer
#: serving family (scoring buckets + prefill/decode/migrate): a cold
#: boot compiles + serializes every executable next to the snapshot, a
#: fresh process LOADS the family — gates are the boot-to-/readyz
#: ratio (cold >= ELASTIC_BOOT_RATIO_FLOOR x warm) and ZERO recompiles
#: over a mixed infer+generate stream after the load.  (B) The
#: autoscaling balancer riding a closed-loop traffic ramp plus seeded
#: preemption of HALF the initial fleet: scale-up must land (cache-
#: warm boot) within ELASTIC_SCALEUP_DEADLINE_S, goodput holds a band
#: of the pre-chaos baseline, the ledger stays exactly-once, and the
#: idle settle window drains the fleet back toward the quorum.  Phase
#: B rides the thin MNIST fleet model (it measures COORDINATION, same
#: reasoning as --fleet); phase A carries the compile-heavy family
#: where the cache earns its keep.  Both bands are RELATIVE, per the
#: standing cgroup-swing discipline.
ELASTIC_SEED = 1702
ELASTIC_BOOT_RATIO_FLOOR = 3.0  # cold boot >= 3x cache-warm boot
ELASTIC_REPLICAS = 4            # initial fleet; chaos preempts half
ELASTIC_MAX = 6                 # autoscale_max
ELASTIC_MIN = 2                 # min_replicas quorum
ELASTIC_BASE_QPS = 20.0         # open-loop baseline offered load
ELASTIC_BASE_S = 6.0
ELASTIC_CHAOS_S = 18.0          # ramp + preemption window
ELASTIC_SETTLE_S = 25.0         # idle window: scale-down must fire
ELASTIC_INFLIGHT = 64           # closed-loop ramp pressure
ELASTIC_SCALEUP_DEADLINE_S = 40.0
ELASTIC_GOODPUT_BAND = 0.5      # chaos goodput >= band x baseline
ELASTIC_GEN_STREAM = ((3, 24), (5, 40), (12, 30), (8, 44), (14, 36))


def elastic_main() -> None:
    """``--elastic``: the zero-cold-start elasticity gates (ISSUE 17),
    one JSON line; gates AFTER the line so a trip never destroys the
    record."""
    import shutil
    import tempfile
    import time as _time

    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.parallel.chaos import (FaultSchedule, FleetScaler,
                                          ReplicaHarness,
                                          SubtreePreempter)
    from znicz_tpu.serving import (InferenceClient, InferenceServer,
                                   ReplicaBalancer)
    from znicz_tpu.serving import aot_cache

    if not aot_cache.available():
        raise SystemExit("this jax build cannot serialize executables "
                         "— the AOT cache gate cannot run")
    sys.setswitchinterval(1e-3)
    tmp = tempfile.mkdtemp(prefix="znicz_elastic_")

    # ---- phase A: the AOT cache on the full transformer family ----------
    root.charlm.loader.update({"n_train": 64, "n_valid": 16,
                               "seq_len": GEN_TRAIN_LEN})
    root.charlm.model.update(dict(SEQ_MODEL))

    from znicz_tpu.samples.charlm import CharLMWorkflow

    def charlm_wf():
        prng.reset(1013)        # bit-identical params every build
        wf = CharLMWorkflow()
        wf.initialize(device=None)
        return wf

    wf_a = charlm_wf()
    wf_a.snapshotter.directory = os.path.join(tmp, "charlm")
    path_a = wf_a.snapshotter.save("elastic_a")
    root.common.serving.seq.rungs = list(GEN_SEQ_RUNGS)
    root.common.serving.generate.update({
        "enabled": True, "page_size": GEN_PAGE_SIZE,
        "slots": GEN_SLOTS})
    # dir="" -> the cache lands in aot_cache/ NEXT TO the snapshot
    root.common.serving.aot_cache.update({"enabled": True, "dir": ""})
    rng = np.random.default_rng(ELASTIC_SEED)
    vocab = SEQ_MODEL["vocab"]

    def prompt_of(length):
        return rng.integers(1, vocab, size=length).astype(np.uint8)

    def drive_mixed(cli):
        """The mixed stream the zero-recompile proof rides: scoring
        requests across the seq ladder + generations that cross the
        cache-rung migration."""
        for ln in (3, 10, 16, 40, 64, 7):
            cli.infer(prompt_of(ln)[None])
        for p_len, max_new in ELASTIC_GEN_STREAM:
            rep = cli.generate(prompt_of(p_len),
                               max_new_tokens=max_new)
            assert len(rep["tokens"]) >= 1

    boots = []
    ref_y = None
    probe = prompt_of(12)[None]     # ONE pinned probe — the parity
    # gate scores the same bytes through both boots
    for which in ("cold", "warm"):
        wf = charlm_wf()
        srv = InferenceServer(wf, snapshot=path_a,
                              max_batch=GEN_MAX_BATCH,
                              max_delay_ms=5.0,
                              queue_bound=8 * GEN_MAX_BATCH).start()
        cli = InferenceClient(srv.endpoint, timeout=120,
                              breaker_failures=0)
        y = cli.infer(probe)
        if ref_y is None:
            ref_y = y
        parity = bool(np.array_equal(ref_y, y))
        compiles_post_boot = srv.runner.compiles
        drive_mixed(cli)
        jit_total = (srv.runner.jit_cache_size() or 0) + \
            (srv.gen_sched.gen.jit_cache_size() or 0)
        boots.append({
            "which": which,
            "boot_to_ready_s": round(srv.boot_to_ready_s, 3),
            "warm_report": srv.warm_report,
            "parity_vs_cold": parity,
            "recompiles_mixed_stream":
                srv.runner.compiles - compiles_post_boot,
            "jit_cache_after_stream": jit_total,
            "aot": srv.runner._aot_cache.stats()})
        cli.close()
        srv.stop()
    cold, warm = boots
    boot_ratio = cold["boot_to_ready_s"] / max(
        warm["boot_to_ready_s"], 1e-9)
    # phase A config off before phase B's scoring-only fleet
    root.common.serving.generate.enabled = False
    root.common.serving.seq.rungs = None

    # ---- phase B: the autoscaler rides a ramp + preemption --------------
    fleet_dir = os.path.join(tmp, "fleet")
    wf_f = _build_fleet_workflow()
    wf_f.snapshotter.directory = fleet_dir
    path_f = wf_f.snapshotter.save("elastic_fleet")
    # prewarm the fleet family once so EVERY fleet boot below is
    # cache-warm — the elasticity story depends on it
    pre = InferenceServer(_build_fleet_workflow(), snapshot=path_f,
                          max_batch=FLEET_MAX_BATCH).start()
    fleet_cold_boot_s = pre.boot_to_ready_s
    pre.stop()

    balancer = ReplicaBalancer(
        replica_ttl_s=1.2, failover_timeout_s=1.0, failover_tries=4,
        hedge_floor_s=0.4, min_replicas=ELASTIC_MIN).start()

    wfs = [_build_fleet_workflow() for _ in range(ELASTIC_REPLICAS)]
    binds = ["tcp://127.0.0.1:*"] * ELASTIC_REPLICAS

    def make_factory(i):
        def make():
            return InferenceServer(
                wfs[i], bind=binds[i], snapshot=path_f,
                max_batch=FLEET_MAX_BATCH, max_delay_ms=2.0,
                queue_bound=64, announce=balancer.endpoint,
                replica_id=f"r{i}")
        return make

    harnesses = [ReplicaHarness(make_factory(i))
                 for i in range(ELASTIC_REPLICAS)]
    for i, h in enumerate(harnesses):
        h.start()
        binds[i] = h.server.endpoint

    class _SpawnedReplica:
        """FleetScaler handle for one autoscaler-spawned replica."""

        def __init__(self, i):
            self.replica_id = f"s{i}"
            self.server = None

        def start(self):
            self.server = InferenceServer(
                _build_fleet_workflow(), snapshot=path_f,
                max_batch=FLEET_MAX_BATCH, max_delay_ms=2.0,
                queue_bound=64, announce=balancer.endpoint,
                replica_id=self.replica_id).start()
            return self

        def kill(self):
            if self.server is not None:
                self.server.stop()

    class _HarnessHandle:
        """Retire adapter: a scale-down of an initial replica kills
        its harness for good (settle-phase only — the preemption
        schedule has already run by then)."""

        def __init__(self, rid, harness):
            self.replica_id = rid
            self._h = harness

        def kill(self):
            self._h.kill()

    scaler = FleetScaler(_SpawnedReplica)
    for i, h in enumerate(harnesses):
        scaler.adopt(_HarnessHandle(f"r{i}", h))

    t0 = _time.perf_counter()
    while balancer.ready_count() < ELASTIC_REPLICAS:
        if _time.perf_counter() - t0 > 120:
            raise SystemExit("elastic fleet never became ready")
        _time.sleep(0.05)

    cli = InferenceClient(balancer.endpoint, timeout=25.0,
                          resend_after_s=60.0, breaker_failures=0)
    x1 = rng.normal(0, 1, (1, 28 * 28)).astype(np.float32)
    infer_rids = set()
    answers: dict = {}
    warm_seen: dict = {}            # replica_id -> (warm_source, boot_s)

    def pump(wait=0.002):
        for rep in cli.collect(wait):
            rid = rep.get("req_id")
            if rid not in infer_rids:
                continue
            if rid in answers:
                raise SystemExit(f"req {rid} answered twice — "
                                 f"exactly-once broken")
            answers[rid] = bool(rep.get("ok"))

    def note_members():
        for row in balancer.stats()["replicas"]:
            if row["warm_source"] is not None:
                warm_seen[row["replica_id"]] = (row["warm_source"],
                                                row["boot_s"])

    def ok_count():
        return sum(1 for ok in answers.values() if ok)

    def drive_open(duration_s, qps):
        n0 = ok_count()
        t0 = _time.perf_counter()
        i = 0
        while _time.perf_counter() - t0 < duration_s:
            now = _time.perf_counter() - t0
            if now >= i / qps and cli.in_flight < 256:
                infer_rids.add(cli.submit(x1))
                i += 1
            pump()
        return ok_count() - n0, _time.perf_counter() - t0

    def drain(budget_s=25.0):
        t0 = _time.perf_counter()
        while cli.in_flight and _time.perf_counter() - t0 < budget_s:
            pump(0.02)

    # B1: pre-chaos baseline (autoscaler not armed yet)
    ok_base, el_base = drive_open(ELASTIC_BASE_S, ELASTIC_BASE_QPS)
    drain()
    goodput_base = ok_base / el_base
    note_members()

    # B2: arm the autoscaler, then ramp + preempt half the fleet
    balancer.enable_autoscale(
        scaler.spawn, scaler.retire, autoscale_max=ELASTIC_MAX,
        autoscale_high_load=0.75, autoscale_low_load=0.05,
        autoscale_up_after=2, autoscale_down_after=6,
        autoscale_eval_s=0.25, autoscale_cooldown_s=1.5,
        autoscale_drain_timeout_s=8.0,
        autoscale_boot_deadline_s=ELASTIC_SCALEUP_DEADLINE_S)
    preempters = [
        SubtreePreempter(FaultSchedule(ELASTIC_SEED + 1),
                         [("r0", harnesses[0].kill,
                           harnesses[0].restart)],
                         kill_s=(2.0, 4.0), down_s=(2.0, 3.0)),
        SubtreePreempter(FaultSchedule(ELASTIC_SEED + 2),
                         [("r1", harnesses[1].kill,
                           harnesses[1].restart)],
                         kill_s=(7.0, 9.0), down_s=(2.0, 3.0)),
    ]
    for p in preempters:
        p.start()
    t_ramp0 = _time.perf_counter()
    scaled_ready_at = None
    n0 = ok_count()
    while _time.perf_counter() - t_ramp0 < ELASTIC_CHAOS_S:
        while cli.in_flight < ELASTIC_INFLIGHT:
            infer_rids.add(cli.submit(x1))
        pump()
        if scaled_ready_at is None:
            for row in balancer.stats()["replicas"]:
                if row["replica_id"].startswith("s") and row["ready"]:
                    scaled_ready_at = _time.perf_counter() - t_ramp0
        note_members()
    el_chaos = _time.perf_counter() - t_ramp0
    for p in preempters:
        p.join(timeout=60)
    drain()
    ok_chaos = ok_count() - n0
    goodput_chaos = ok_chaos / el_chaos
    note_members()
    scale_ups = balancer.scale_ups

    # B3: idle settle — the low band must drain back down
    t0 = _time.perf_counter()
    while _time.perf_counter() - t0 < ELASTIC_SETTLE_S:
        pump(0.05)
        if balancer.scale_downs >= 1 and \
                _time.perf_counter() - t0 > 5.0:
            break
    scale_downs = balancer.scale_downs
    note_members()
    unanswered = [r for r in infer_rids if r not in answers]
    ledger = balancer.ledger()
    members_final = balancer.member_count()
    spawned_warm = {rid: ws for rid, ws in warm_seen.items()
                    if rid.startswith("s")}

    record = {
        "metric": "elastic_boot_ratio",
        "value": round(boot_ratio, 2),
        "unit": "cold_boot_over_cache_warm_boot",
        "ratio_floor": ELASTIC_BOOT_RATIO_FLOOR,
        "cold": cold,
        "warm": warm,
        "family": (cold["warm_report"] or {}).get("expected"),
        "fleet_cold_boot_s": round(fleet_cold_boot_s, 3),
        "seed": ELASTIC_SEED,
        "replicas": ELASTIC_REPLICAS,
        "goodput_base": round(goodput_base, 2),
        "goodput_chaos": round(goodput_chaos, 2),
        "goodput_band": ELASTIC_GOODPUT_BAND,
        "preemptions": sum(p.preemptions for p in preempters),
        "scale_ups": scale_ups,
        "scale_downs": scale_downs,
        "scaleup_ready_s": None if scaled_ready_at is None
        else round(scaled_ready_at, 2),
        "scaleup_deadline_s": ELASTIC_SCALEUP_DEADLINE_S,
        "warm_sources": warm_seen,
        "members_final": members_final,
        "unanswered": len(unanswered),
        "ledger": ledger,
        "failovers": balancer.failovers,
        "heals": balancer.heals,
        "replicas_lost": balancer.replicas_lost,
    }
    print(json.dumps(record))
    cli.close()
    balancer.stop()
    scaler.stop_all()
    for h in harnesses:
        h.kill()
    root.common.serving.aot_cache.update({"enabled": False, "dir": ""})
    # gates AFTER the JSON line (the record survives a trip)
    failures = []
    if boot_ratio < ELASTIC_BOOT_RATIO_FLOOR:
        failures.append(
            f"cache-warm boot only {boot_ratio:.2f}x faster than cold "
            f"(floor {ELASTIC_BOOT_RATIO_FLOOR}x)")
    for b in (cold, warm):
        rep = b["warm_report"] or {}
        if not rep.get("ok"):
            failures.append(f"{b['which']} boot warm proof failed: "
                            f"{rep}")
        if b["recompiles_mixed_stream"]:
            failures.append(
                f"{b['recompiles_mixed_stream']} recompiles in the "
                f"{b['which']} boot's mixed stream (must be 0)")
        if b["jit_cache_after_stream"]:
            failures.append(
                f"{b['which']} boot: {b['jit_cache_after_stream']} "
                f"implicit jit cache entries slipped past the AOT "
                f"tables")
        if not b["parity_vs_cold"]:
            failures.append(f"{b['which']} boot answers diverged")
    wrep = warm["warm_report"] or {}
    if wrep.get("cache_hits") != wrep.get("expected"):
        failures.append(f"warm boot did not load the whole family "
                        f"from cache: {wrep}")
    if scale_ups < 1:
        failures.append("the ramp never triggered a scale-up")
    if scaled_ready_at is None:
        failures.append(
            f"no autoscaled replica became ready within the "
            f"{ELASTIC_CHAOS_S}s chaos window")
    elif scaled_ready_at > ELASTIC_SCALEUP_DEADLINE_S:
        failures.append(
            f"scale-up took {scaled_ready_at:.1f}s > deadline "
            f"{ELASTIC_SCALEUP_DEADLINE_S}s")
    bad_warm = {rid: ws for rid, ws in spawned_warm.items()
                if ws[0] != "cache_hit"}
    if bad_warm:
        failures.append(f"autoscaled replicas booted WITHOUT the "
                        f"cache: {bad_warm}")
    if goodput_chaos < ELASTIC_GOODPUT_BAND * goodput_base:
        failures.append(
            f"chaos goodput {goodput_chaos:.1f}/s < "
            f"{ELASTIC_GOODPUT_BAND} x baseline {goodput_base:.1f}/s")
    if sum(p.preemptions for p in preempters) < 2:
        failures.append("the seeded schedule preempted fewer than "
                        "half the initial fleet")
    if scale_downs < 1:
        failures.append("the idle settle never drained the grown "
                        "fleet (no scale-down)")
    if not ledger["balanced"] or ledger["in_flight"]:
        failures.append(f"ledger leaked: {ledger}")
    if unanswered:
        failures.append(f"{len(unanswered)} acknowledged requests "
                        f"never answered (no reply, no refusal)")
    shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        raise SystemExit("elastic gates failed: " + "; ".join(failures))


#: --ingest gate knobs: the injected decode delay is calibrated to the
#: measured warm segment time (so the gate is structural, not an absolute
#: speed bet this host's swinging cgroup share can lose), clamped to
#: [floor, cap]; the gate then asserts the training thread's staged-
#: segment wait stays under INGEST_GATE_FRAC of the injected delay.
INGEST_DELAY_FLOOR_S = 0.02
INGEST_DELAY_CAP_S = 0.5
INGEST_GATE_FRAC = 0.5


def _build_ingest_workflow(delay_s: float, hidden: int, n_train: int,
                           n_valid: int, mb: int, max_epochs: int):
    """A host-staged streaming run (regime 3) whose decode path sleeps
    ``delay_s`` per segment gather — the injected stall the double buffer
    must absorb.  Shared by ``--ingest`` and the lean tier-1 test."""
    import time as _time

    from znicz_tpu.core import prng
    from znicz_tpu.core.mutable import Bool
    from znicz_tpu.loader.streaming import HostArraySource, StreamingLoader
    from znicz_tpu.standard_workflow import StandardWorkflow

    class DelayedSource(HostArraySource):
        """HostArraySource with a fixed sleep in the gather (decode)
        path — sleep, not spin: the injected stall must be absorbable by
        a thread that overlaps it, exactly like real PIL decode/IO."""

        delay_s = 0.0
        gathers = 0

        def gather(self, idx):
            type(self).gathers += 1
            if self.delay_s:
                _time.sleep(self.delay_s)
            return super().gather(idx)

    prng.reset(1013)
    rng = np.random.default_rng(7)
    n = n_train + n_valid
    data = (rng.random((n, 28, 28)) * 255).astype(np.uint8)
    labels = rng.integers(0, 10, n).astype(np.int32)
    src = DelayedSource(data, labels)
    src.delay_s = float(delay_s)
    gd = {"learning_rate": 0.01, "gradient_moment": 0.9}
    layers = [
        {"type": "all2all_strict_relu",
         "->": {"output_sample_shape": hidden}, "<-": dict(gd)},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": dict(gd)},
    ]
    wf = StandardWorkflow(
        name="IngestBench",
        loader=StreamingLoader(name="loader", source=src,
                               minibatch_size=mb,
                               class_lengths=[0, n_valid, n_train],
                               device_budget_bytes=0),
        layers=layers, loss_function="softmax",
        decision_config={"max_epochs": max_epochs, "fail_iterations": 0})
    wf.initialize(device=None)
    wf.snapshotter.gate_skip = Bool(True)   # measure ingest, not IO
    return wf, src


def run_ingest_overlap(delay_s: float = None, hidden: int = 2048,
                       n_train: int = 1024, n_valid: int = 128,
                       mb: int = 64, max_epochs: int = 3,
                       with_off: bool = True) -> dict:
    """The structural overlap measurement (ISSUE 7 satellite, the PR-6
    async-snapshot gate's shape): calibrate the warm segment time with no
    delay, inject ``delay_s`` (default: half the measured segment time,
    clamped) into the decode path, and record the training thread's
    per-segment staged wait — the double buffer absorbs the delay, so the
    wait must stay well under it even though EVERY segment's assembly
    slept that long on the stager worker.  Returns the measurement dict
    (gating is the caller's job — bench gates, the lean test asserts)."""
    import time as _time

    from znicz_tpu.core.config import root as _root
    from znicz_tpu.parallel.fused import FusedTrainer

    # phase 1 — calibrate: no delay, async staging on (warm compile too)
    wf, _src = _build_ingest_workflow(0.0, hidden, n_train, n_valid, mb,
                                      max_epochs=1)
    tr = FusedTrainer(wf)
    tr.run()
    warm_steps = max(tr.stats["warm_steps"], 1)
    step_s = (tr.stats["warm_wall_s"] / warm_steps
              if tr.stats["warm_wall_s"] > 0
              else tr.stats["wall_s"] / max(tr.stats["train_steps"], 1))
    segment_s = step_s * max(tr.scan_chunk, 1)
    if delay_s is None:
        delay_s = min(max(0.5 * segment_s, INGEST_DELAY_FLOOR_S),
                      INGEST_DELAY_CAP_S)
    # phase 2 — the gated run: delay injected, async staging ON
    wf2, src2 = _build_ingest_workflow(delay_s, hidden, n_train, n_valid,
                                       mb, max_epochs)
    t0 = _time.perf_counter()
    tr2 = FusedTrainer(wf2)
    tr2.run()
    on_wall = _time.perf_counter() - t0
    st = tr2._stager.stats() if tr2._stager is not None else None
    # phase 3 — context: same run, async staging OFF (every segment pays
    # the delay inline on the training thread); reported, not gated — the
    # structural gate above is what must hold on any host.  The lean
    # tier-1 test skips it (with_off=False): its assertions are all on
    # the ON run.
    off_wall = None
    if with_off:
        was_staging = _root.common.engine.get("async_staging", True)
        _root.common.engine.async_staging = False
        try:
            wf3, _ = _build_ingest_workflow(delay_s, hidden, n_train,
                                            n_valid, mb, max_epochs)
            t0 = _time.perf_counter()
            FusedTrainer(wf3).run()
            off_wall = _time.perf_counter() - t0
        finally:
            _root.common.engine.async_staging = was_staging
    return {
        "delay_ms": round(delay_s * 1e3, 2),
        "calibrated_segment_ms": round(segment_s * 1e3, 2),
        "scan_chunk": int(tr2.scan_chunk),
        "stager": st,
        "wait_ms_max": (None if st is None else st["wait_ms_max"]),
        "gate_frac": INGEST_GATE_FRAC,
        "segment_gathers": int(src2.gathers),
        "compiles": int(tr2._m_compiles.value),
        "jit_cache_sizes": tr2.jit_cache_sizes(),
        "wall_s_async_on": round(on_wall, 3),
        "wall_s_async_off": (None if off_wall is None
                             else round(off_wall, 3)),
        "on_vs_off": (round(off_wall / on_wall, 3)
                      if on_wall and off_wall is not None else None),
    }


def check_ingest_overlap(vals: dict, max_epochs: int) -> list:
    """The structural findings for one overlap run (shared by the bench
    gate and the tier-1 test; empty list = gate holds):

      - the stager engaged and (beyond the run's cold-start group) no
        dispatch group missed the double buffer;
      - the MEDIAN staged wait sits well under the injected delay — the
        hot loop (train segments following train segments) absorbed it;
      - waits near the delay are CONFINED to the per-epoch boundary
        groups: each epoch's first assembly cannot start before the tail
        is consumed (the lookahead must not advance past a tail — the
        snapshot at an epoch boundary must record tail state; resume
        parity), so one un-absorbed wait per epoch + the cold start is
        the structural floor, and MORE than that means the overlap broke.
    """
    bad = []
    st = vals["stager"]
    if st is None:
        return ["async staging did not engage (stager is None) — the "
                "gate requires the host-staged regime"]
    if st["stage_hits"] < 1 or st["stage_misses"] > 1:
        bad.append(f"dispatch groups missed the double buffer: "
                   f"hits={st['stage_hits']} misses={st['stage_misses']}")
    delay_ms = vals["delay_ms"]
    p50 = st["wait_ms_p50"]
    if p50 is None or p50 > INGEST_GATE_FRAC * delay_ms:
        bad.append(f"median staged wait {p50}ms is not well under the "
                   f"injected {delay_ms}ms decode delay — the hot loop "
                   "is not absorbing it")
    big = [w for w in st["wait_ms_window"]
           if w > INGEST_GATE_FRAC * delay_ms]
    if len(big) > max_epochs + 1:
        bad.append(f"{len(big)} staged waits exceeded "
                   f"{INGEST_GATE_FRAC} x the delay ({big}) — more than "
                   f"the {max_epochs} epoch-boundary groups + cold "
                   "start; steady-state segments are stalling")
    return bad


def ingest_main() -> None:
    """``--ingest``: the ingest/compute overlap gate (ISSUE 7), one JSON
    line; FAILS (after the line — the record survives a trip) per
    ``check_ingest_overlap``."""
    max_epochs = 3
    vals = run_ingest_overlap(max_epochs=max_epochs)
    st = vals["stager"]
    p50 = None if st is None else st["wait_ms_p50"]
    print(json.dumps({
        "metric": "ingest_overlap_wait_ms_p50",
        "value": p50,
        "unit": "ms",
        "vs_baseline": (round(p50 / vals["delay_ms"], 5)
                        if p50 is not None else None),
        **vals,
    }))
    bad = check_ingest_overlap(vals, max_epochs)
    if bad:
        raise SystemExit("ingest overlap gate failed:\n  "
                         + "\n  ".join(bad))


def telemetry_main() -> None:
    """``--telemetry``: the telemetry-layer overhead gate (ISSUE 5), one
    JSON line.  Drives the REAL fused training hot loop
    (``FusedTrainer.run`` over a small MNIST MLP) in interleaved windows
    with the telemetry layer enabled vs disabled
    (``telemetry.set_enabled``: spans + the trainer's step histogram —
    the optional layer; service accounting counters predate telemetry
    and run either way), and FAILS if the enabled best-of step time
    exceeds the disabled best-of by more than ``TELEMETRY_GATE_PCT``
    percent.  The gate is relative and same-process, so it holds on this
    TPU-less container and transfers unchanged to a TPU host."""
    import time as _time

    from znicz_tpu import telemetry
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root as _root
    from znicz_tpu.core.mutable import Bool
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    _root.mnist.loader.n_train = 2048
    _root.mnist.loader.n_valid = 256
    _root.mnist.loader.n_test = 0
    _root.mnist.loader.minibatch_size = 256
    _root.mnist.decision.max_epochs = 10_000    # windows drive epochs
    _root.mnist.layers = [256, 10]
    try:
        wf = mnist.MnistWorkflow()
    finally:
        _root.mnist.layers = [100, 10]
    wf.initialize(device=None)
    wf.snapshotter.gate_skip = Bool(True)   # isolate the telemetry layer
    trainer = FusedTrainer(wf)
    d = wf.decision

    def window(enabled: bool) -> float:
        """Per-step wall time of one TELEMETRY_EPOCHS-epoch run
        continuation (the decision is re-armed; loader/prng state flows
        on, so every window runs the same kind of steps)."""
        telemetry.set_enabled(enabled)
        d.complete.set(False)
        d.max_epochs = int(d.epoch_number) + 1 + TELEMETRY_EPOCHS
        s0 = trainer.steps_done
        t0 = _time.perf_counter()
        trainer.run()
        dt = _time.perf_counter() - t0
        return dt / max(trainer.steps_done - s0, 1)

    window(True)                    # compile + cache warm, both variants
    window(False)
    best_on = best_off = float("inf")
    rounds = []
    overhead_pct = float("inf")
    for _ in range(TELEMETRY_MAX_ROUNDS):
        best_off = min(best_off, window(False))
        best_on = min(best_on, window(True))
        overhead_pct = 100.0 * (best_on / best_off - 1.0)
        rounds.append({"off_step_ms": round(best_off * 1e3, 4),
                       "on_step_ms": round(best_on * 1e3, 4),
                       "overhead_pct": round(overhead_pct, 3)})
        if overhead_pct <= TELEMETRY_GATE_PCT:
            break                   # gate met; no need to re-roll
    telemetry.set_enabled(True)
    print(json.dumps({
        "metric": "telemetry_overhead_pct",
        "value": round(overhead_pct, 3),
        "unit": "%",
        "vs_baseline": round(best_on / best_off, 5),
        "gate_pct": TELEMETRY_GATE_PCT,
        "step_ms_disabled": round(best_off * 1e3, 4),
        "step_ms_enabled": round(best_on * 1e3, 4),
        "epochs_per_window": TELEMETRY_EPOCHS,
        "rounds": rounds,
        "spans_recorded": telemetry.tracer().recorded,
        "metric_samples": sum(
            1 for ln in telemetry.render_prometheus().splitlines()
            if ln and not ln.startswith("#")),
    }))
    # gate AFTER the JSON line (the record survives a trip)
    if overhead_pct > TELEMETRY_GATE_PCT:
        raise SystemExit(
            f"telemetry overhead {overhead_pct:.3f}% exceeds the "
            f"{TELEMETRY_GATE_PCT}% gate on the training hot loop")


#: --obs protocol knobs (ISSUE 20): the fleet observability plane.
#: Three gates, one JSON line.  (1) The PR 5 overhead bar re-run on the
#: SERVING hot loop: interleaved set_enabled(on/off) windows over a
#: pipelined closed-loop infer stream against a real InferenceServer —
#: relative and same-process, so it holds on this swinging-cgroup host
#: and transfers to a TPU host unchanged.  (2) A seeded chaos run over
#: scripted replicas (zero warmup: the gate is about the JOURNAL, not
#: the model): a blackholed replica under flood forces a failover, a
#: forced-high autoscaler band spawns, and a parity-mismatching swap
#: rolls back — the event journal must contain that causal chain with
#: first-occurrence order failover < autoscale_up < rollback and
#: strictly monotone seqs.  (3) Stitching across REAL OS processes: two
#: subprocess charlm generation replicas announce to an in-process
#: balancer; one generation request must land in the fleet trace store
#: as a single trace_id crossing >=3 fleet origins on >=2 distinct OS
#: pids (client + balancer in this interpreter, frontend/scheduler
#: spans shipped back on heartbeats and reply summaries from a child).
OBS_SEED = 2008
OBS_GATE_PCT = 2.0          # enabled may cost at most this much
OBS_WINDOW_REQS = 300       # closed-loop requests per on/off window
OBS_INFLIGHT = 16           # client pipeline depth in the windows
OBS_MAX_ROUNDS = 6          # bounded interleaved best-of pairs
OBS_CHAOS_STAGE_S = 20.0    # per-stage flood budget in the chaos run
OBS_GEN_REPLICAS = 2        # subprocess generation replicas
OBS_GEN_BOOT_S = 300.0      # child compile+announce budget (1 core)
OBS_STITCH_S = 60.0         # generation stitching budget

#: The gate-3 child: a real OS process running one tiny charlm
#: generation replica that announces to the parent's balancer.  Spans
#: ride its heartbeats; params are seed-pinned so both children answer
#: bit-identically (routing stays free).
_OBS_CHILD = """
import sys
from znicz_tpu.core import prng
from znicz_tpu.core.config import root
root.charlm.loader.update({"n_train": 64, "n_valid": 16, "n_test": 0,
                           "seq_len": 32, "minibatch_size": 16})
root.charlm.model.update({"vocab": 32, "embed": 32, "heads": 2,
                          "ffn": 64})
root.common.serving.seq.rungs = [8, 32]
root.common.serving.generate.update({"enabled": True, "page_size": 8,
                                     "slots": 4})
prng.reset(1013)
from znicz_tpu.samples.charlm import CharLMWorkflow
from znicz_tpu.serving import InferenceServer
wf = CharLMWorkflow()
wf.initialize(device=None)
srv = InferenceServer(wf, max_batch=4, max_delay_ms=1.0,
                      announce=sys.argv[1],
                      replica_id=sys.argv[2]).start()
sys.stdin.read()        # parent closes stdin -> clean exit
srv.stop()
"""


def obs_main() -> None:
    """``--obs``: the fleet observability gates (ISSUE 20), one JSON
    line; gates AFTER the line so a trip never destroys the record."""
    import subprocess
    import time as _time

    from znicz_tpu import telemetry
    from znicz_tpu.parallel.chaos import FleetScaler, ScriptedReplica
    from znicz_tpu.serving import (InferenceClient, InferenceServer,
                                   ReplicaBalancer)

    sys.setswitchinterval(1e-3)
    telemetry.set_enabled(True)
    rng = np.random.default_rng(OBS_SEED)

    # ---- gate 1: serving hot-loop overhead, interleaved on/off ----------
    srv = InferenceServer(_build_fleet_workflow(),
                          max_batch=FLEET_MAX_BATCH, max_delay_ms=1.0,
                          queue_bound=64).start()
    cli = InferenceClient(srv.endpoint, timeout=30.0,
                          breaker_failures=0)
    x1 = rng.normal(0, 1, (1, 28 * 28)).astype(np.float32)

    def window(enabled: bool) -> float:
        """Per-request wall time of one pipelined closed-loop window
        (submission capped at OBS_INFLIGHT in flight)."""
        telemetry.set_enabled(enabled)
        sent = done = 0
        t0 = _time.perf_counter()
        while done < OBS_WINDOW_REQS:
            while sent < OBS_WINDOW_REQS and \
                    cli.in_flight < OBS_INFLIGHT:
                cli.submit(x1)
                sent += 1
            done += sum(1 for _ in cli.collect(0.001))
        return (_time.perf_counter() - t0) / OBS_WINDOW_REQS

    window(True)                    # compile + cache warm, both variants
    window(False)
    best_on = best_off = float("inf")
    rounds = []
    overhead_pct = float("inf")
    for _ in range(OBS_MAX_ROUNDS):
        best_off = min(best_off, window(False))
        best_on = min(best_on, window(True))
        overhead_pct = 100.0 * (best_on / best_off - 1.0)
        rounds.append({"off_req_ms": round(best_off * 1e3, 4),
                       "on_req_ms": round(best_on * 1e3, 4),
                       "overhead_pct": round(overhead_pct, 3)})
        if overhead_pct <= OBS_GATE_PCT:
            break                   # gate met; no need to re-roll
    telemetry.set_enabled(True)
    cli.close()
    srv.stop()

    # ---- gate 2: seeded chaos -> the journal's causal chain -------------
    cur0 = telemetry.journal().last_seq
    bal = ReplicaBalancer(replica_ttl_s=1.0, heartbeat_s=0.25,
                          failover_timeout_s=0.5, failover_tries=4,
                          hedge=False, canary_requests=6,
                          parity_every=2, canary_timeout_s=20.0,
                          min_replicas=2).start()
    reps = [ScriptedReplica(bal.endpoint, f"r{i}",
                            snapshots={"diff": 3.0}).start()
            for i in range(2)]
    t0 = _time.time()
    while bal.ready_count() < 2:
        if _time.time() - t0 > 20:
            raise SystemExit("obs chaos fleet never became ready")
        _time.sleep(0.02)
    cli2 = InferenceClient(bal.endpoint, timeout=10.0,
                           breaker_failures=0, resend_after_s=30.0)
    x4 = np.arange(4, dtype=np.float32).reshape(1, 4) + 1.0

    def flood(pred, budget_s=OBS_CHAOS_STAGE_S):
        """Closed-loop flood until ``pred`` holds (refusals during the
        swap wave are expected traffic, not errors)."""
        t0 = _time.perf_counter()
        while _time.perf_counter() - t0 < budget_s:
            try:
                cli2.result(cli2.submit(x4), timeout=8)
            except Exception:
                pass
            if pred():
                return True
        return pred()

    # stage A (preemption under flood): a blackholed replica swallows
    # dispatches; the failover timeout re-dispatches them
    hole = ScriptedReplica(bal.endpoint, "hole", blackhole=True).start()
    t0 = _time.time()
    while "hole" not in {m["replica_id"]
                         for m in bal.stats()["replicas"]}:
        if _time.time() - t0 > 10:
            raise SystemExit("blackhole replica never joined")
        _time.sleep(0.02)
    failover_ok = flood(lambda: bal.failovers >= 1)
    # stage B: a forced-high band spawns through the FleetScaler
    scaler = FleetScaler(
        lambda i: ScriptedReplica(bal.endpoint, f"s{i}",
                                  snapshots={"diff": 3.0}))
    bal.enable_autoscale(
        scaler.spawn, scaler.retire, autoscale_max=4,
        autoscale_high_load=-1.0, autoscale_low_load=-2.0,
        autoscale_up_after=2, autoscale_down_after=2,
        autoscale_eval_s=0.05, autoscale_cooldown_s=0.05,
        autoscale_drain_timeout_s=5.0)
    scale_ok = flood(lambda: bal.scale_ups >= 1)
    # neutralize the band (neither high nor low can fire) and clear the
    # blackhole so the swap wave's canary probes cannot be swallowed
    bal.enable_autoscale(
        scaler.spawn, scaler.retire, autoscale_max=4,
        autoscale_high_load=1e9, autoscale_low_load=-1.0)
    hole.kill()
    t0 = _time.time()
    while "hole" in {m["replica_id"]
                     for m in bal.stats()["replicas"]}:
        if _time.time() - t0 > 15:
            break
        _time.sleep(0.05)
    # stage C: a parity-mismatching swap must auto-roll-back
    cli2._send({"cmd": "swap", "path": "diff"})
    rollback_ok = flood(lambda: bal.rollbacks >= 1, budget_s=40.0)

    events = telemetry.journal().since(cur0)
    seqs = [e["seq"] for e in events]
    monotone = all(b > a for a, b in zip(seqs, seqs[1:]))
    first: dict = {}
    for e in events:
        first.setdefault(e["kind"], e["seq"])
    chain = [{"kind": k, "seq": first.get(k)}
             for k in ("failover", "autoscale_up", "rollback")]
    chain_ok = (None not in [c["seq"] for c in chain]
                and chain[0]["seq"] < chain[1]["seq"] < chain[2]["seq"])
    scale_evt = next((e for e in events
                      if e["kind"] == "autoscale_up"), {})
    cli2.close()
    bal.stop()
    scaler.stop_all()
    for r in reps:
        r.kill()

    # ---- gate 3: one generation request stitched across OS processes ----
    bal3 = ReplicaBalancer(replica_ttl_s=2.5, heartbeat_s=0.25).start()
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _OBS_CHILD, bal3.endpoint, f"g{i}"],
        stdin=subprocess.PIPE, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL, env=env)
        for i in range(OBS_GEN_REPLICAS)]
    my_pid = str(os.getpid())

    def stitched_gen_trace():
        """A trace crossing >=3 fleet origins with at least one span
        from a DIFFERENT OS pid (gate-2 leftovers can't qualify: their
        spans all carry this interpreter's pid)."""
        for tid, members in telemetry.fleet_trace().traces().items():
            origins: list = []
            for o, _ in members:
                if o not in origins:
                    origins.append(o)
            pids = {o.rsplit("@", 1)[-1] for o in origins}
            if len(origins) >= 3 and any(p != my_pid for p in pids):
                if all(s.get("args", {}).get("trace_id") == tid
                       for _, s in members):
                    return tid, origins, pids, members
        return None

    stitched = None
    gen_replies = 0
    try:
        t0 = _time.time()
        while bal3.ready_count() < OBS_GEN_REPLICAS:
            for p in procs:
                if p.poll() is not None:
                    raise SystemExit(
                        f"obs generation child exited rc={p.returncode} "
                        f"before announcing")
            if _time.time() - t0 > OBS_GEN_BOOT_S:
                raise SystemExit("obs generation fleet never became "
                                 "ready")
            _time.sleep(0.2)
        boot_s = _time.time() - t0
        cli3 = InferenceClient(bal3.endpoint, timeout=90.0,
                               breaker_failures=0)
        deadline = _time.time() + OBS_STITCH_S
        while _time.time() < deadline and stitched is None:
            prompt = rng.integers(1, 32, size=6).astype(np.uint8)
            rep = cli3.generate(prompt, max_new_tokens=8, timeout=90)
            assert len(rep["tokens"]) >= 1
            gen_replies += 1
            _time.sleep(0.05)
            stitched = stitched_gen_trace()
        cli3.close()
    finally:
        for p in procs:
            try:
                p.stdin.close()
            except Exception:
                pass
        for p in procs:
            try:
                p.wait(timeout=20)
            except Exception:
                p.kill()
        bal3.stop()

    tid, origins, pids, members = stitched or (None, [], set(), [])
    names = sorted({s.get("name", "") for _, s in members})
    print(json.dumps({
        "metric": "obs_overhead_pct",
        "value": round(overhead_pct, 3),
        "unit": "%",
        "gate_pct": OBS_GATE_PCT,
        "req_ms_disabled": round(best_off * 1e3, 4),
        "req_ms_enabled": round(best_on * 1e3, 4),
        "window_reqs": OBS_WINDOW_REQS,
        "rounds": rounds,
        "seed": OBS_SEED,
        "chaos": {
            "events": len(events),
            "monotone_seqs": monotone,
            "chain": chain,
            "autoscale_load": scale_evt.get("load"),
            "failovers": failover_ok,
            "scale_ups": scale_ok,
            "rollbacks": rollback_ok,
        },
        "stitched": {
            "trace_id": tid,
            "origins": origins,
            "os_pids": sorted(pids),
            "spans": len(members),
            "names": names,
            "gen_replies": gen_replies,
            "fleet_boot_s": round(boot_s, 1),
        },
    }))
    # gates AFTER the JSON line (the record survives a trip)
    failures = []
    if overhead_pct > OBS_GATE_PCT:
        failures.append(
            f"observability overhead {overhead_pct:.3f}% exceeds the "
            f"{OBS_GATE_PCT}% gate on the serving hot loop")
    if not (failover_ok and scale_ok and rollback_ok):
        failures.append(
            f"chaos stages incomplete: failover={failover_ok} "
            f"autoscale={scale_ok} rollback={rollback_ok}")
    if not monotone:
        failures.append("journal seqs are not strictly monotone")
    if not chain_ok:
        failures.append(
            f"journal lacks the failover -> autoscale_up -> rollback "
            f"causal chain: {chain}")
    if "load" not in scale_evt:
        failures.append("the autoscale_up event does not carry the "
                        "load numbers that drove it")
    if stitched is None:
        failures.append(
            f"no generation trace stitched across >=3 fleet origins "
            f"and >=2 OS pids within {OBS_STITCH_S:.0f}s "
            f"({gen_replies} generations served)")
    elif len(pids) < 2:
        failures.append(f"stitched trace stayed inside one OS "
                        f"process: {sorted(pids)}")
    if failures:
        raise SystemExit("obs gates failed: " + "; ".join(failures))


def _gd_finals(decision) -> dict:
    from znicz_tpu.loader.base import TRAIN, VALID

    return {"final_train_loss": round(decision.epoch_metrics[TRAIN]["loss"], 6),
            "valid_err_pct": round(decision.epoch_metrics[VALID]["err_pct"], 3),
            "epochs": int(decision.epoch_number) + 1}


def _mse_finals(decision) -> dict:
    from znicz_tpu.loader.base import TRAIN, VALID

    return {"final_train_mse": round(decision.epoch_metrics[TRAIN]["loss"], 6),
            "valid_mse": round(decision.epoch_metrics[VALID]["loss"], 6),
            "epochs": int(decision.epoch_number) + 1}


def _som_finals(decision) -> dict:
    return {"final_qerror": round(decision.epoch_qerror[-1], 6),
            "first_qerror": round(decision.epoch_qerror[0], 6),
            "epochs": len(decision.epoch_qerror)}


#: BASELINE config index -> (sample module name, finals extractor)
SAMPLE_CONFIGS = [
    (0, "mnist", _gd_finals),
    (1, "cifar", _gd_finals),
    (2, "mnist_ae", _mse_finals),
    (3, "kohonen", _som_finals),
]

#: Anchor tolerance BANDS (VERDICT r4 item 6 — defend, don't re-record):
#: {config: {metric: (center, half_width)}}.  Centers are the BASELINE.md
#: anchors; a change that moves a seeded final outside its band makes
#: --samples exit non-zero until BASELINE.md documents a side-by-side
#: justification (both formulations, same seeds) and re-centers the band.
#: Runs are seeded and CPU-pinned, so the widths absorb jax-version and
#: platform drift, not run-to-run noise.
ANCHOR_BANDS = {
    0: {"final_train_loss": (0.0109, 0.005), "valid_err_pct": (0.875, 0.5)},
    1: {"final_train_loss": (0.9501, 0.05), "valid_err_pct": (44.0, 1.5)},
    2: {"final_train_mse": (2.0818, 0.1), "valid_mse": (2.1689, 0.1)},
    3: {"final_qerror": (0.0505, 0.02)},
}


def check_anchor(config: int, vals: dict) -> list:
    """Out-of-band findings for one config's finals: a list of
    {metric, value, center, band} dicts (empty = all within band)."""
    out = []
    for metric, (center, half) in ANCHOR_BANDS.get(config, {}).items():
        if abs(vals[metric] - center) > half:
            out.append({"metric": metric, "value": vals[metric],
                        "center": center, "band": half})
    return out


def measure_samples() -> None:
    """BASELINE configs 0-3 at their default sample configs; one JSON line
    each (the BASELINE.md "Measured" column), each checked against its
    ANCHOR_BANDS tolerance; exits non-zero on any out-of-band final."""
    import importlib

    from znicz_tpu.core import prng

    failures = []
    for config, name, finals in SAMPLE_CONFIGS:
        prng.reset(1013)
        module = importlib.import_module(f"znicz_tpu.samples.{name}")
        wf = module.run()
        vals = finals(wf.decision)
        bad = check_anchor(config, vals)
        failures += [{"sample": name, **f} for f in bad]
        band_checks = {
            metric: {"center": center, "band": half,
                     "ok": not any(f["metric"] == metric for f in bad)}
            for metric, (center, half) in ANCHOR_BANDS.get(config,
                                                           {}).items()}
        print(json.dumps({"config": config, "sample": name, **vals,
                          "anchor_bands": band_checks}))
    if failures:
        print(json.dumps({"anchor_band_failures": failures}),
              file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    args = sys.argv[1:]
    if "--batch" in args:
        # labeled protocol VARIANT (not the headline): e.g. --batch 512
        # amortizes the constant per-step weight+optimizer HBM traffic
        # over more images (VERDICT r3 item 3c)
        BATCH = int(args[args.index("--batch") + 1])
        STEPS = max(1, (200 * 128) // BATCH)    # same images per window
        HEADLINE_GUARDS = False
    if "--master-bf16" in args:
        # labeled VARIANT: bf16-STORED master weights (f32 update math) —
        # halves the per-step param read+write traffic but changes
        # convergence semantics (weight rounding); never the headline
        from znicz_tpu.core.config import root as _r

        _r.common.engine.master_dtype = "bfloat16"
        HEADLINE_GUARDS = False
    if "--fused-elementwise" in args:
        # labeled VARIANT until BASELINE.md records the with/without
        # numbers: route the conv1/conv2 LRN+ReLU+pool block through the
        # single-pass Pallas kernel (znicz_tpu/pallas_fused_block.py).
        # Same protocol, same loss gates; the JSON line records the flag
        # so with/without runs are directly comparable.
        from znicz_tpu.core.config import root as _r

        _r.common.engine.fused_elementwise = True
        HEADLINE_GUARDS = False
    if "--fused-tail" in args:
        # labeled VARIANT mirroring --fused-elementwise (ISSUE 7): the
        # conv3-5 bias+ReLU, FC bias+ReLU+dropout and softmax-xent+grad
        # epilogues run fused (root.common.engine.fused_tail).  Combine
        # with --fused-elementwise for the full-fusion run; the
        # BASELINE.md r12 protocol is the with/without ladder.
        from znicz_tpu.core.config import root as _r

        _r.common.engine.fused_tail = True
        HEADLINE_GUARDS = False
    if "--samples" in args:
        measure_samples()
    elif "--telemetry" in args:
        telemetry_main()
    elif "--obs" in args:
        obs_main()
    elif "--ingest" in args:
        ingest_main()
    elif "--wire" in args:
        wire_main()
    elif "--agg" in args:
        agg_main()
    elif "--serve" in args:
        serve_main()
    elif "--fleet" in args:
        fleet_main()
    elif "--shard" in args:
        shard_main()
    elif "--shard-train" in args:
        shard_train_main()
    elif "--seq" in args:
        seq_main()
    elif "--generate" in args:
        generate_main()
    elif "--prefix" in args:
        prefix_main()
    elif "--elastic" in args:
        elastic_main()
    elif "--stream" in args:
        stream_main()
    elif "--product" in args:
        product_main()
    else:
        main(legacy="--legacy" in args)
