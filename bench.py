"""Benchmark harness: AlexNet fused-train-step throughput on the attached
chip (BASELINE.md north-star metric).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Protocol (unsoftened AlexNet — VERDICT r1 item 3):
  - full 1000-class fc8 (the real AlexNet head);
  - 1024 resident training images (227x227x3) + 128 validation;
  - FRESH minibatch indices every step, drawn by driving the Loader state
    machine exactly like ``FusedTrainer.run`` does — the gather/input path
    varies per step and per epoch (reshuffle), nothing is cached;
  - the whole timed window is ONE ``lax.scan`` dispatch of STEPS train
    steps (the FusedTrainer's own scan path) — one executable launch, so
    the number measures device math, not per-dispatch link latency; the
    headline is the MEDIAN of three independently-timed windows
    (``elapsed_s_runs`` records all three);
  - a jax.profiler trace of a post-timing scan lands in ``bench_profile/``
    (best-effort: some remote platforms cannot trace).

``vs_baseline`` divides by 500 img/s — the widely published cuDNN-Caffe
AlexNet training throughput on a K40, standing in for the reference's own
number, which is unobtainable here (BASELINE.md: reference mount empty, no
network).  Update BASELINE.json.published when a real number lands.

Timing barrier: the timed window ends by PULLING VALUES to the host (last
loss + one element of every updated param) rather than
``jax.block_until_ready`` — on the tunneled "axon" platform
block_until_ready returns before the device finishes, so the r1/r2 numbers
(64.6k/75.1k img/s) were dispatch-rate artifacts, ~4x above what the chip
can physically do (the r3 self-validation below caught this: they implied
211% MFU on a 197-TFLOP/s v5e; a chained-matmul probe confirmed
block_until_ready returns in ~0.2ms where the math needs >100ms).

Self-validation (VERDICT r2 item 1): the JSON line carries
``flops_per_step`` (analytic, from the built layer shapes — convention:
MACs x 2 for every conv/GEMM, backward = 2x forward for weighted layers,
i.e. train = 3x forward; elementwise/pool/LRN ops are not counted),
``xla_flops_per_step`` (XLA's own cost model for the compiled step, a
cross-check on the analytic number), ``tflops_per_sec``, ``mfu_vs_peak``
(against a bf16 peak table keyed on ``device_kind`` — ``null`` with
``peak_tflops: null`` when the chip is unknown), and ``loss_untrained`` /
``loss_first`` / ``loss_last``; the bench FAILS if any timed loss is
non-finite or the timed tail is not well below the untrained starting
loss (the tail alone may oscillate at convergence — STEPS steps over the
resident set is dozens of epochs).

``python bench.py --samples`` instead measures the BASELINE configs 0-3
finals (MNIST / CIFAR / MnistAE / Kohonen at their default sample configs)
and prints one JSON line per config — the numbers recorded in BASELINE.md's
"Measured" column.

``python bench.py --legacy`` re-runs the round-1 protocol (100-class head,
256 resident images, FIXED minibatch indices) so the two protocols can be
compared on the same host/build (ADVICE r2: the recorded r1 vs r2 numbers
came from different local runs and were not comparable).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

K40_ALEXNET_IMG_S = 500.0   # documented stand-in (see module docstring)

BATCH = 128
STEPS = 200     # one scan dispatch; long enough to amortize the final host
                # sync (~100ms on tunneled platforms) to ~1% of the window;
                # warmup is one full same-length scan (compile reuse)
N_TRAIN = 1024
N_VALID = 128
N_CLASSES = 1000
PROFILE_DIR = "bench_profile"

#: dense bf16 peak TFLOP/s per chip, keyed by substrings of
#: ``jax.devices()[0].device_kind`` (public spec-sheet numbers).  The first
#: matching row wins; no match -> peak unknown -> mfu_vs_peak is null.
PEAK_TFLOPS_BF16 = [
    (("v6",), 918.0),                  # v6e / Trillium
    (("v5", "lite"), 197.0),           # v5e ("TPU v5 lite")
    (("v5e",), 197.0),
    (("v5",), 459.0),                  # v5p
    (("v4",), 275.0),
    (("v3",), 123.0),
    (("v2",), 46.0),
]


def peak_tflops(device_kind: str):
    kind = device_kind.lower()
    for needles, peak in PEAK_TFLOPS_BF16:
        if all(n in kind for n in needles):
            return peak
    return None


def analytic_train_flops(workflow, batch: int) -> int:
    """Analytic flops for ONE train step of the built workflow, from the
    actual initialized layer shapes.  Convention (stated in the module
    docstring): 2 flops per MAC; backward = 2x forward for every weighted
    layer (one GEMM/conv for d_input, one for d_weights) -> train = 3x
    forward MACs x 2.  Elementwise/pool/LRN/loss flops are excluded (<1%
    for AlexNet-class nets)."""
    from znicz_tpu.all2all import All2All
    from znicz_tpu.conv import Conv

    fwd_macs = 0
    for f in workflow.forwards:
        if isinstance(f, Conv):
            b, oh, ow, k = f.output.shape
            c = f.input.shape[-1]
            fwd_macs += batch * oh * ow * k * f.ky * f.kx * c
        elif isinstance(f, All2All):
            out_n = f.output_samples_number
            in_n = int(np.prod(f.input.shape[1:]))
            fwd_macs += batch * out_n * in_n
    return int(fwd_macs * 2 * 3)


def xla_flops(step, *args):
    """XLA's own cost model for the compiled step (best-effort; None when
    the platform/jax version does not expose it)."""
    try:
        cost = step.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):    # older jax: one dict/device
            cost = cost[0]
        return int(cost["flops"]) if cost and "flops" in cost else None
    except Exception as exc:
        print(f"xla cost_analysis unavailable: {exc!r}", file=sys.stderr)
        return None


def main(legacy: bool = False) -> None:
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root

    prng.seed_all(1013)
    root.common.engine.precision = "bfloat16"   # params fp32, MXU bf16
    root.alexnet.loader.minibatch_size = BATCH
    root.alexnet.loader.n_train = 2 * BATCH if legacy else N_TRAIN
    root.alexnet.loader.n_valid = BATCH if legacy else N_VALID
    root.alexnet.loader.n_classes = 100 if legacy else N_CLASSES
    root.alexnet.decision.max_epochs = 10_000   # bench drives steps itself

    import jax

    from znicz_tpu.loader.base import TRAIN
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples.alexnet import AlexNetWorkflow

    wf = AlexNetWorkflow()
    wf.initialize(device=None)
    trainer = FusedTrainer(wf)
    scan = trainer.make_train_scan()
    params = trainer.extract_params()
    vels = trainer.extract_velocities()
    dataset = wf.loader.original_data.devmem
    targets = wf.loader.original_labels.devmem
    # the scan takes per-step hypers rows (LR-schedule support);
    # the bench uses constant hypers
    hypers_mat = trainer.tiled_hypers(STEPS)

    wf.loader.indices_only = True     # the scan gathers on device itself

    def draw_minibatches(n):
        """n fresh TRAIN minibatches from the loader state machine (epoch
        boundaries reshuffle, exactly as in training) -> stacked index
        matrix + batch sizes.  ``legacy`` freezes the first minibatch
        (the r1 protocol's fixed-indices softening)."""
        idx, bs = [], []
        while len(idx) < n:
            wf.loader.run()
            if wf.loader.minibatch_class == TRAIN:
                idx.append(np.array(wf.loader.minibatch_indices.mem,
                                    np.int32))
                bs.append(wf.loader.minibatch_size)
        if legacy:
            idx = [idx[0]] * n
            bs = [bs[0]] * n
        return np.stack(idx), np.asarray(bs, np.int32)

    base_key = prng.get("bench").jax_base_key()

    def steps_from(start):
        return np.arange(start, start + STEPS, dtype=np.int32)

    @jax.jit
    def _probe(params, losses):
        """One tiny array depending on the step losses AND one element of
        every updated param — forcing it forces the whole scan."""
        import jax.numpy as jnp

        vals = [jnp.sum(losses).astype(jnp.float32)]
        for layer in params.values():
            for arr in layer.values():
                vals.append(arr[(0,) * arr.ndim].astype(jnp.float32))
        return jnp.stack(vals)

    def materialize(params, losses):
        """Force REAL completion by pulling VALUES to the host in a single
        transfer.  On some tunneled platforms (axon) ``block_until_ready``
        returns before the device finishes, which silently turned r1/r2's
        numbers into dispatch-rate measurements (>4x inflated) —
        transferred values cannot be faked.  One fused transfer, because
        each host round-trip costs ~100ms through the tunnel."""
        return float(np.asarray(_probe(params, losses))[0])

    flops_step = analytic_train_flops(wf, BATCH)
    # warmup at the SAME scan length so the timed call reuses the compile
    idx_mat, bs_vec = draw_minibatches(STEPS)
    params, vels, ms, _conf = scan(params, vels, hypers_mat, dataset, targets,
                            idx_mat[:, :], bs_vec, base_key, steps_from(0))
    materialize(params, ms[0])
    warmup_losses = [float(l) for l in np.asarray(ms[0])]
    # XLA's cost model counts the scan (while-loop) body ONCE, so the
    # lowered scan's flops ARE the per-step flops
    xla_flops_step = xla_flops(
        scan, params, vels, hypers_mat, dataset, targets, idx_mat, bs_vec,
        base_key, steps_from(0))

    # three independently-timed windows, each restarted from the SAME
    # post-warmup state (device copies; the timed scans donate the
    # copies).  Restarting matters: letting the windows keep training
    # (800+ steps over 1024 resident images) drives the net into
    # bf16-overflow territory — the bench's own NaN check caught that.
    # The MEDIAN is the headline — robust to a one-off host/tunnel hiccup.
    import jax.numpy as jnp

    base_params = jax.tree_util.tree_map(jnp.copy, params)
    base_vels = jax.tree_util.tree_map(jnp.copy, vels)
    runs = []
    losses_per_run = []
    for r in range(3):
        idx_mat, bs_vec = draw_minibatches(STEPS)
        p = jax.tree_util.tree_map(jnp.copy, base_params)
        v = jax.tree_util.tree_map(jnp.copy, base_vels)
        t0 = time.perf_counter()        # ~1ms of copies may drain in-queue
        p, v, ms, _conf = scan(p, v, hypers_mat, dataset, targets,
                        idx_mat, bs_vec, base_key, steps_from(STEPS))
        materialize(p, ms[0])
        runs.append(time.perf_counter() - t0)
        losses_per_run.append(ms[0])
    elapsed = float(np.median(runs))
    ms = (losses_per_run[int(np.argsort(runs)[1])],)

    # the timed window must be REAL training: every loss finite, and the
    # trajectory (warmup start -> timed tail) clearly descending.  The tail
    # alone may sit on a converged plateau (STEPS steps over N_TRAIN
    # resident images = dozens of epochs), so the decrease is asserted
    # against the untrained starting loss, with margin.
    losses = [float(l) for l in np.asarray(ms[0])]
    assert all(np.isfinite(l) for l in losses), f"non-finite loss: {losses}"
    tail = float(np.mean(losses[-10:]))
    assert tail < 0.5 * warmup_losses[0], (
        f"training did not progress: start {warmup_losses[0]:.4f} -> "
        f"timed tail mean {tail:.4f}")

    # post-timing profiler trace (never perturbs the measurement above)
    try:
        with jax.profiler.trace(PROFILE_DIR):
            params, vels, ms, _conf = scan(params, vels, hypers_mat, dataset, targets,
                                    idx_mat, bs_vec, base_key,
                                    steps_from(3000))
            materialize(params, ms[0])
        print(f"profiler trace -> {PROFILE_DIR}/", file=sys.stderr)
    except Exception as exc:                      # platform can't trace
        print(f"profiler trace unavailable: {exc!r}", file=sys.stderr)

    img_s = BATCH * STEPS / elapsed
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "unknown")
    peak = peak_tflops(kind)
    tflops = flops_step * STEPS / elapsed / 1e12
    print(json.dumps({
        "metric": ("alexnet_imagenet_train_throughput_legacy_r1_protocol"
                   if legacy else "alexnet_imagenet_train_throughput"),
        "value": round(img_s, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s / K40_ALEXNET_IMG_S, 3),
        "batch": BATCH, "steps": STEPS, "elapsed_s": round(elapsed, 4),
        "elapsed_s_runs": [round(r, 4) for r in runs],
        "flops_per_step": flops_step,
        "xla_flops_per_step": xla_flops_step,
        "flops_convention": "2*MACs, train=3x fwd, conv+GEMM only",
        "tflops_per_sec": round(tflops, 2),
        "device_kind": kind,
        "platform": getattr(dev, "platform", "unknown"),
        "peak_tflops_bf16": peak,
        "mfu_vs_peak": round(tflops / peak, 4) if peak else None,
        "loss_untrained": round(warmup_losses[0], 4),
        "loss_first": round(losses[0], 4),
        "loss_last": round(losses[-1], 4),
    }))


def _gd_finals(decision) -> dict:
    from znicz_tpu.loader.base import TRAIN, VALID

    return {"final_train_loss": round(decision.epoch_metrics[TRAIN]["loss"], 6),
            "valid_err_pct": round(decision.epoch_metrics[VALID]["err_pct"], 3),
            "epochs": int(decision.epoch_number) + 1}


def _mse_finals(decision) -> dict:
    from znicz_tpu.loader.base import TRAIN, VALID

    return {"final_train_mse": round(decision.epoch_metrics[TRAIN]["loss"], 6),
            "valid_mse": round(decision.epoch_metrics[VALID]["loss"], 6),
            "epochs": int(decision.epoch_number) + 1}


def _som_finals(decision) -> dict:
    return {"final_qerror": round(decision.epoch_qerror[-1], 6),
            "first_qerror": round(decision.epoch_qerror[0], 6),
            "epochs": len(decision.epoch_qerror)}


#: BASELINE config index -> (sample module name, finals extractor)
SAMPLE_CONFIGS = [
    (0, "mnist", _gd_finals),
    (1, "cifar", _gd_finals),
    (2, "mnist_ae", _mse_finals),
    (3, "kohonen", _som_finals),
]


def measure_samples() -> None:
    """BASELINE configs 0-3 at their default sample configs; one JSON line
    each (the BASELINE.md "Measured" column)."""
    import importlib

    from znicz_tpu.core import prng

    for config, name, finals in SAMPLE_CONFIGS:
        prng.reset(1013)
        module = importlib.import_module(f"znicz_tpu.samples.{name}")
        wf = module.run()
        print(json.dumps({"config": config, "sample": name,
                          **finals(wf.decision)}))


if __name__ == "__main__":
    if "--samples" in sys.argv[1:]:
        measure_samples()
    else:
        main(legacy="--legacy" in sys.argv[1:])
