// znicz_native: the host-side native runtime of the TPU rebuild.
//
// The reference's native layer was OpenCL/CUDA kernels + libzmq; on TPU the
// device side is XLA's job, but the HOST data path (the part of the
// reference that lived in C via numpy/libzmq) is rebuilt here in C++:
//
//   - xorshift128+ PRNG — the same generator family as the reference's
//     rand.cl/rand.cu device kernels (veles/prng), used for shuffling and
//     host-side fills;
//   - Fisher-Yates minibatch shuffling (the loader's hot host op);
//   - batched row gather (minibatch assembly for host-resident datasets);
//   - u8 -> f32 scale/shift decode (image loader normalization).
//
// Exposed as a plain C ABI consumed via ctypes (znicz_tpu/native.py); every
// entry point has a numpy fallback so the framework runs without a
// compiler.  Build: g++ -O3 -march=native -shared -fPIC.

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <cmath>

extern "C" {

// ---- xorshift128+ (state: 2x uint64, caller-owned) -------------------------

static inline uint64_t xs128p_next(uint64_t *s) {
    uint64_t s1 = s[0];
    const uint64_t s0 = s[1];
    const uint64_t result = s0 + s1;
    s[0] = s0;
    s1 ^= s1 << 23;
    s[1] = s1 ^ s0 ^ (s1 >> 18) ^ (s0 >> 5);
    return result;
}

void znicz_seed(uint64_t *state, uint64_t seed) {
    // splitmix64 expansion (never leave the state all-zero)
    uint64_t z = seed + 0x9E3779B97F4A7C15ULL;
    for (int i = 0; i < 2; ++i) {
        z += 0x9E3779B97F4A7C15ULL;
        uint64_t x = z;
        x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
        x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
        state[i] = x ^ (x >> 31);
    }
    if (state[0] == 0 && state[1] == 0) state[0] = 1;
}

void znicz_fill_uniform(uint64_t *state, float *out, size_t n,
                        float low, float high) {
    const float span = high - low;
    for (size_t i = 0; i < n; ++i) {
        // 53-bit mantissa trick -> double in [0,1)
        const double u = (double)(xs128p_next(state) >> 11) * 0x1.0p-53;
        out[i] = low + (float)u * span;
    }
}

void znicz_fill_normal(uint64_t *state, float *out, size_t n, float stddev) {
    // Box-Muller, pairwise
    size_t i = 0;
    while (i < n) {
        double u1 = (double)(xs128p_next(state) >> 11) * 0x1.0p-53;
        double u2 = (double)(xs128p_next(state) >> 11) * 0x1.0p-53;
        if (u1 < 1e-300) u1 = 1e-300;
        const double r = std::sqrt(-2.0 * std::log(u1));
        out[i++] = (float)(r * std::cos(2.0 * M_PI * u2)) * stddev;
        if (i < n)
            out[i++] = (float)(r * std::sin(2.0 * M_PI * u2)) * stddev;
    }
}

void znicz_shuffle_i32(uint64_t *state, int32_t *arr, size_t n) {
    if (n < 2) return;
    for (size_t i = n - 1; i > 0; --i) {
        const size_t j = (size_t)(xs128p_next(state) % (uint64_t)(i + 1));
        const int32_t t = arr[i];
        arr[i] = arr[j];
        arr[j] = t;
    }
}

// ---- minibatch assembly ----------------------------------------------------

void znicz_gather_f32(const float *src, const int32_t *idx, float *dst,
                      size_t n_rows, size_t row_elems) {
    const size_t row_bytes = row_elems * sizeof(float);
    for (size_t r = 0; r < n_rows; ++r)
        std::memcpy(dst + r * row_elems,
                    src + (size_t)idx[r] * row_elems, row_bytes);
}

void znicz_u8_to_f32(const uint8_t *src, float *dst, size_t n,
                     float scale, float shift) {
    for (size_t i = 0; i < n; ++i)
        dst[i] = (float)src[i] * scale + shift;
}

// ---- version ---------------------------------------------------------------

int znicz_native_abi(void) { return 1; }

}  // extern "C"
